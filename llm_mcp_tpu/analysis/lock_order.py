"""Lock-order pass: the OrderedLock rank discipline, checked before runtime.

`utils/locks.py` enforces rank order at acquire time — but only on the
code path some thread actually walks, which is exactly the paths soak
tests miss. This pass makes the discipline static:

1. **Rank map extraction** — every `OrderedLock(name, rank)` construction
   in the package, with `rank=` resolved through module-level integer
   constants (`MIGRATION_LOCK_RANK`). Duplicate ranks and duplicate names
   are findings: two locks sharing a rank can deadlock each other while
   the runtime check stays silent (equal is rejected at acquire, so the
   first nesting raises — but only at runtime).
2. **Doc drift** — the extracted map must match the rank table in
   doc/concurrency.md row for row. The table is regenerated from this
   pass's map (`python -m llm_mcp_tpu.analysis --write-lock-table`), so
   after this PR it *cannot* drift; the check catches hand edits.
3. **Acquisition-order audit** — a conservative interprocedural walk:
   every `with <lock>:` whose context expression resolves to a ranked
   lock opens a held scope; inside it, directly nested ranked `with`s and
   calls whose (transitive) may-acquire set contains a rank <= the held
   rank are findings.

Call resolution is deliberately narrow — `self.method()` to the enclosing
class, `name()` to a same-module function, `self.attr.method()` through a
global `self.attr = ClassName(...)` assignment census (unambiguous attr
names only). Narrow means no false positives from duck typing; the
runtime check stays the backstop for dynamic dispatch.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .core import Finding, RepoIndex, int_constants

PASS_ID = "lock-order"

# doc/concurrency.md rank-table markers (also used by --write-lock-table)
TABLE_BEGIN = "<!-- lock-rank-table:begin"
TABLE_END = "<!-- lock-rank-table:end -->"
_ROW_RE = re.compile(r"^\|\s*(\d+)\s*\|\s*`([^`]+)`")


@dataclass
class LockDef:
    name: str
    rank: int
    path: str
    line: int
    cls: str | None  # enclosing class when constructed as self.X = ...
    attr: str | None  # the attribute it is bound to


@dataclass
class _Acq:
    """One direct ranked acquisition inside a function."""

    rank: int
    lock: str
    line: int


@dataclass
class _FuncInfo:
    qualname: str  # "module.py::Class.method" or "module.py::func"
    path: str
    direct: list[_Acq] = field(default_factory=list)
    # calls made anywhere in the body: resolved callee qualnames
    calls: list[str] = field(default_factory=list)


def extract_lock_defs(index: RepoIndex) -> tuple[list[LockDef], list[Finding]]:
    defs: list[LockDef] = []
    findings: list[Finding] = []
    for relpath in index.package_files():
        tree = index.ast(relpath)
        if tree is None:
            continue
        consts = int_constants(tree)
        for node, cls in _walk_with_class(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "OrderedLock"
            ):
                continue
            name = rank = None
            args = list(node.args)
            if args and isinstance(args[0], ast.Constant):
                name = args[0].value
            if len(args) > 1:
                rank = _resolve_int(args[1], consts)
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
                if kw.arg == "rank":
                    rank = _resolve_int(kw.value, consts)
            if relpath.endswith("utils/locks.py"):
                continue  # the class's own repr/docstring examples
            if not isinstance(name, str) or rank is None:
                findings.append(
                    Finding(
                        PASS_ID, relpath, node.lineno,
                        f"unresolved:{relpath}:{ast.unparse(node)[:60]}",
                        "OrderedLock construction with non-literal name or "
                        "rank — the static rank map cannot see it",
                    )
                )
                continue
            defs.append(
                LockDef(name, rank, relpath, node.lineno, cls,
                        _bound_attr(node)))
    return defs, findings


def _walk_with_class(tree: ast.Module):
    """(node, enclosing_class_name) for every node."""

    def rec(node: ast.AST, cls: str | None):
        for child in ast.iter_child_nodes(node):
            child_cls = child.name if isinstance(child, ast.ClassDef) else cls
            yield child, child_cls
            yield from rec(child, child_cls)

    yield from rec(tree, None)


def _resolve_int(expr: ast.expr, consts: dict[str, int]) -> int | None:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return expr.value
    if isinstance(expr, ast.Name):
        return consts.get(expr.id)
    return None


def _bound_attr(call: ast.Call) -> str | None:
    """The `X` of `self.X = OrderedLock(...)` / `X = OrderedLock(...)`,
    recovered from the parent assignment (RepoIndex attaches
    `_lint_parent` links at parse time — core.attach_parents)."""
    parent = getattr(call, "_lint_parent", None)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Attribute):
            return tgt.attr
        if isinstance(tgt, ast.Name):
            return tgt.id
    return None


def parse_doc_table(text: str) -> dict[str, int] | None:
    """name -> rank from the concurrency doc's rank table. Uses the
    marker block when present, else every `| N | \\`name\\` |` row."""
    begin = text.find(TABLE_BEGIN)
    end = text.find(TABLE_END)
    region = text[begin:end] if 0 <= begin < end else text
    rows: dict[str, int] = {}
    for line in region.splitlines():
        m = _ROW_RE.match(line.strip())
        if m:
            rows[m.group(2)] = int(m.group(1))
    return rows or None


class LockOrderPass:
    pass_id = PASS_ID

    def run(self, index: RepoIndex) -> list[Finding]:
        findings: list[Finding] = []
        defs, extract_findings = extract_lock_defs(index)
        findings.extend(extract_findings)
        findings.extend(self._uniqueness(defs))
        findings.extend(self._doc_drift(index, defs))
        findings.extend(self._order_audit(index, defs))
        return findings

    # -- checks -------------------------------------------------------------

    def _uniqueness(self, defs: list[LockDef]) -> list[Finding]:
        out: list[Finding] = []
        by_rank: dict[int, LockDef] = {}
        by_name: dict[str, LockDef] = {}
        for d in defs:
            prev = by_rank.get(d.rank)
            if prev and prev.name != d.name:
                out.append(
                    Finding(
                        PASS_ID, d.path, d.line,
                        f"dup-rank:{d.rank}:{prev.name}+{d.name}",
                        f"locks {prev.name!r} ({prev.path}) and {d.name!r} "
                        f"share rank {d.rank} — they can never nest and the "
                        "runtime check only catches it when they do",
                    )
                )
            by_rank.setdefault(d.rank, d)
            prev = by_name.get(d.name)
            if prev and prev.rank != d.rank:
                out.append(
                    Finding(
                        PASS_ID, d.path, d.line,
                        f"dup-name:{d.name}:{prev.rank}+{d.rank}",
                        f"lock name {d.name!r} constructed with two ranks "
                        f"({prev.rank} at {prev.path}:{prev.line}, "
                        f"{d.rank} here)",
                    )
                )
            by_name.setdefault(d.name, d)
        return out

    def _doc_drift(
        self, index: RepoIndex, defs: list[LockDef]
    ) -> list[Finding]:
        doc_rel = index.config["doc_concurrency"]
        text = index.text(doc_rel)
        if text is None:
            return [
                Finding(
                    PASS_ID, doc_rel, 0, "doc-missing",
                    f"{doc_rel} not found — the rank table must exist",
                )
            ]
        doc = parse_doc_table(text)
        if doc is None:
            return [
                Finding(
                    PASS_ID, doc_rel, 0, "doc-no-table",
                    f"no rank table rows found in {doc_rel}",
                )
            ]
        code = {d.name: d.rank for d in defs}
        out: list[Finding] = []
        for name, rank in sorted(code.items()):
            if name not in doc:
                out.append(
                    Finding(
                        PASS_ID, doc_rel, 0, f"doc-missing-lock:{name}",
                        f"lock {name!r} (rank {rank}) is constructed in code "
                        f"but has no row in {doc_rel} — run "
                        "`python -m llm_mcp_tpu.analysis --write-lock-table`",
                    )
                )
            elif doc[name] != rank:
                out.append(
                    Finding(
                        PASS_ID, doc_rel, 0,
                        f"doc-rank-drift:{name}:{doc[name]}!={rank}",
                        f"doc says {name!r} has rank {doc[name]}, code says "
                        f"{rank} — regenerate the table",
                    )
                )
        for name in sorted(set(doc) - set(code)):
            out.append(
                Finding(
                    PASS_ID, doc_rel, 0, f"doc-stale-lock:{name}",
                    f"{doc_rel} documents lock {name!r} that no code "
                    "constructs — delete the row or restore the lock",
                )
            )
        return out

    # -- acquisition-order audit --------------------------------------------

    def _order_audit(
        self, index: RepoIndex, defs: list[LockDef]
    ) -> list[Finding]:
        # lock lookup structures
        by_cls_attr: dict[tuple[str, str], LockDef] = {}
        by_global: dict[tuple[str, str], LockDef] = {}  # (path, var name)
        for d in defs:
            if d.cls and d.attr:
                by_cls_attr[(d.cls, d.attr)] = d
            elif d.attr:
                by_global[(d.path, d.attr)] = d

        # global attr -> class census for self.attr.method() resolution;
        # ambiguous attr names resolve to nothing.
        attr_cls: dict[str, str | None] = {}
        class_files: dict[str, str] = {}
        for relpath in index.package_files():
            tree = index.ast(relpath)
            if tree is None:
                continue
            for node, cls in _walk_with_class(tree):
                if isinstance(node, ast.ClassDef):
                    class_files.setdefault(node.name, relpath)
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                ):
                    attr = node.targets[0].attr
                    cls_name = node.value.func.id
                    if attr in attr_cls and attr_cls[attr] != cls_name:
                        attr_cls[attr] = None  # ambiguous
                    else:
                        attr_cls.setdefault(attr, cls_name)

        def lock_of(expr: ast.expr, relpath: str, cls: str | None):
            """Resolve a with-item context expression to a LockDef."""
            if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name
            ):
                if expr.value.id == "self" and cls:
                    return by_cls_attr.get((cls, expr.attr))
            if isinstance(expr, ast.Name):
                return by_global.get((relpath, expr.id))
            return None

        # pass 1: per-function direct acquisitions + resolved call edges
        funcs: dict[str, _FuncInfo] = {}

        def qual(relpath: str, cls: str | None, name: str) -> str:
            return f"{relpath}::{cls + '.' if cls else ''}{name}"

        for relpath in index.package_files():
            tree = index.ast(relpath)
            if tree is None:
                continue
            module_funcs = {
                n.name for n in tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for node, cls in _walk_with_class(tree):
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                info = _FuncInfo(qual(relpath, cls, node.name), relpath)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            d = lock_of(item.context_expr, relpath, cls)
                            if d:
                                info.direct.append(
                                    _Acq(d.rank, d.name, sub.lineno)
                                )
                    elif isinstance(sub, ast.Call):
                        cq = self._callee_qual(
                            sub, relpath, cls, module_funcs, attr_cls,
                            class_files,
                        )
                        if cq:
                            info.calls.append(cq)
                funcs[info.qualname] = info

        # pass 2: transitive may-acquire closure
        closure: dict[str, set[tuple[int, str]]] = {
            q: {(a.rank, a.lock) for a in i.direct} for q, i in funcs.items()
        }
        changed = True
        while changed:
            changed = False
            for q, info in funcs.items():
                for cq in info.calls:
                    extra = closure.get(cq, set()) - closure[q]
                    if extra:
                        closure[q] |= extra
                        changed = True

        # pass 3: audit every held scope
        findings: list[Finding] = []
        for relpath in index.package_files():
            tree = index.ast(relpath)
            if tree is None:
                continue
            module_funcs = {
                n.name for n in tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for node, cls in _walk_with_class(tree):
                if not isinstance(node, ast.With):
                    continue
                held = [
                    (lock_of(i.context_expr, relpath, cls), i)
                    for i in node.items
                ]
                fn = self._enclosing_function(node)
                where = qual(relpath, cls, fn) if fn else relpath
                for d, _item in held:
                    if d is None:
                        continue
                    for sub in ast.walk(node):
                        if sub is node:
                            continue
                        if isinstance(sub, ast.With):
                            for item in sub.items:
                                inner = lock_of(
                                    item.context_expr, relpath, cls
                                )
                                if inner and inner.rank <= d.rank:
                                    findings.append(
                                        Finding(
                                            PASS_ID, relpath, sub.lineno,
                                            f"nest:{d.name}<-{inner.name}"
                                            f"@{where}",
                                            f"acquires {inner.name!r} (rank "
                                            f"{inner.rank}) while holding "
                                            f"{d.name!r} (rank {d.rank}) — "
                                            "rank must strictly increase",
                                        )
                                    )
                        elif isinstance(sub, ast.Call):
                            cq = self._callee_qual(
                                sub, relpath, cls, module_funcs, attr_cls,
                                class_files,
                            )
                            if not cq:
                                continue
                            for rank, lname in sorted(closure.get(cq, ())):
                                if rank <= d.rank and lname != d.name:
                                    findings.append(
                                        Finding(
                                            PASS_ID, relpath, sub.lineno,
                                            f"call-nest:{d.name}<-{lname}"
                                            f"@{where}->{cq}",
                                            f"call into {cq} may acquire "
                                            f"{lname!r} (rank {rank}) while "
                                            f"holding {d.name!r} (rank "
                                            f"{d.rank})",
                                        )
                                    )
        return findings

    @staticmethod
    def _enclosing_function(node: ast.AST) -> str | None:
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur.name
            cur = getattr(cur, "_lint_parent", None)
        return None

    @staticmethod
    def _callee_qual(
        call: ast.Call,
        relpath: str,
        cls: str | None,
        module_funcs: set[str],
        attr_cls: dict[str, str | None],
        class_files: dict[str, str],
    ) -> str | None:
        f = call.func
        if isinstance(f, ast.Name) and f.id in module_funcs:
            return f"{relpath}::{f.id}"
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                return f"{relpath}::{cls}.{f.attr}"
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                target_cls = attr_cls.get(base.attr)
                if target_cls and target_cls in class_files:
                    return f"{class_files[target_cls]}::{target_cls}.{f.attr}"
        return None


def rank_map(index: RepoIndex) -> dict[str, int]:
    """name -> rank, for --write-lock-table and the JSON report."""
    defs, _ = extract_lock_defs(index)
    return {d.name: d.rank for d in defs}
