"""Dispatch-surface pass: the unified dispatch plane's "no mirror code"
invariant, enforced statically.

PR 17 collapsed the SliceEngine/GenerationEngine fork: ONE scheduling loop
owns policy, and the only multi-host seam is the `DispatchBackend` protocol
(executor/dispatch.py) carrying a serialized (op, host-payload)
step-program. That shape only survives if nothing grows around it — the old
fork began as exactly one hand-mirrored command. This pass fails the build
when backend-specific command handling reappears outside the protocol,
the same way the kernel-parity census keeps Pallas kernels tested:

1. **Vocabulary reconciliation, both ways.** `DISPATCH_OPS` (the published
   step vocabulary in the dispatch module) ⇄ the engine's `_dx("op", ...)`
   call sites ⇄ the `ops["op"] = ...` registrations in `_build_ops`. An op
   dispatched but not published (followers would KeyError), published but
   never dispatched (dead vocabulary row), or dispatched without a
   registration is each its own finding.
2. **No private command channels.** `CmdLeader`/`CmdFollower` may only be
   constructed inside the dispatch module — an engine (or any other
   package module) opening its own wire is per-feature mirror code by
   definition. Re-exports/imports are fine; instantiation is the finding.
3. **One funnel.** Inside the engine module, `*._backend.emit(...)` may be
   called only from `_dx` and `*._backend.run_follower(...)` only from
   `run_follower` — emitting a step outside the funnel desynchronizes
   leader and follower op order, the exact bug class the funnel removes.

AST-only, like every pass here: the engine and dispatch modules are never
imported.
"""

from __future__ import annotations

import ast

from .core import Finding, RepoIndex, string_tuple

PASS_ID = "dispatch-surface"

# Channel primitives that must not be constructed outside the dispatch
# module (check 2).
_CHANNEL_CLASSES = ("CmdLeader", "CmdFollower")

# _backend.<method> → the sole engine function allowed to call it (check 3).
_FUNNELS = {"emit": "_dx", "run_follower": "run_follower"}


def _enclosing_function(node: ast.AST) -> str:
    """Name of the nearest enclosing FunctionDef, "" at module level."""
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
        cur = getattr(cur, "_lint_parent", None)
    return ""


def _dx_call_ops(tree: ast.Module) -> dict[str, int]:
    """op-name → first line of every `<something>._dx("op", ...)` call with
    a string-literal op. Non-literal first args are reported separately."""
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "_dx"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.setdefault(node.args[0].value, node.lineno)
    return out


def _dx_nonliteral_calls(tree: ast.Module) -> list[int]:
    lines: list[int] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "_dx"
            and node.args
            and not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            )
        ):
            lines.append(node.lineno)
    return lines


def _registered_ops(tree: ast.Module) -> dict[str, int]:
    """op-name → line of every `ops["name"] = ...` subscript assignment
    (the `_build_ops` registry convention)."""
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "ops"
                and isinstance(tgt.slice, ast.Constant)
                and isinstance(tgt.slice.value, str)
            ):
                out.setdefault(tgt.slice.value, node.lineno)
    return out


class DispatchSurfacePass:
    pass_id = PASS_ID

    def run(self, index: RepoIndex) -> list[Finding]:
        findings: list[Finding] = []
        findings.extend(self._vocabulary(index))
        findings.extend(self._channel_construction(index))
        findings.extend(self._funnel(index))
        return findings

    # -- 1. vocabulary reconciliation ---------------------------------------

    def _vocabulary(self, index: RepoIndex) -> list[Finding]:
        disp_rel = index.config["dispatch_module"]
        eng_rel = index.config["engine_module"]
        dtree = index.ast(disp_rel)
        etree = index.ast(eng_rel)
        if dtree is None or etree is None:
            missing = disp_rel if dtree is None else eng_rel
            return [
                Finding(
                    PASS_ID, missing, 0, "dispatch-file-missing",
                    f"{missing} not found — dispatch-surface census cannot "
                    "run",
                )
            ]
        published = string_tuple(dtree, "DISPATCH_OPS")
        if published is None:
            return [
                Finding(
                    PASS_ID, disp_rel, 0, "ops-registry-missing",
                    f"no DISPATCH_OPS string-tuple literal in {disp_rel} — "
                    "the step vocabulary must stay statically extractable",
                )
            ]
        dispatched = _dx_call_ops(etree)
        registered = _registered_ops(etree)
        findings: list[Finding] = []
        for line in _dx_nonliteral_calls(etree):
            findings.append(
                Finding(
                    PASS_ID, eng_rel, line, "dx-nonliteral-op",
                    "_dx called with a non-literal op name — the vocabulary "
                    "census cannot see it; dispatch ops must be string "
                    "literals",
                )
            )
        for op in sorted(set(dispatched) - set(published)):
            findings.append(
                Finding(
                    PASS_ID, eng_rel, dispatched[op],
                    f"op-unpublished:{op}",
                    f"engine dispatches op {op!r} that is not in "
                    f"DISPATCH_OPS ({disp_rel}) — followers have no "
                    "contract for it",
                )
            )
        for op in sorted(set(published) - set(dispatched)):
            findings.append(
                Finding(
                    PASS_ID, disp_rel, 0, f"op-undispatched:{op}",
                    f"DISPATCH_OPS entry {op!r} is never dispatched via "
                    "_dx in the engine — dead vocabulary row",
                )
            )
        for op in sorted(set(dispatched) - set(registered)):
            findings.append(
                Finding(
                    PASS_ID, eng_rel, dispatched[op],
                    f"op-unimplemented:{op}",
                    f"engine dispatches op {op!r} with no ops[{op!r}] "
                    "registration in _build_ops — the dispatch would "
                    "KeyError on every backend",
                )
            )
        for op in sorted(set(registered) - set(published)):
            findings.append(
                Finding(
                    PASS_ID, eng_rel, registered[op],
                    f"op-unregistered:{op}",
                    f"_build_ops registers op {op!r} missing from "
                    f"DISPATCH_OPS ({disp_rel}) — publish it or delete it",
                )
            )
        return findings

    # -- 2. channel construction outside the protocol -----------------------

    def _channel_construction(self, index: RepoIndex) -> list[Finding]:
        disp_rel = index.config["dispatch_module"]
        findings: list[Finding] = []
        for rel in index.package_files():
            if rel == disp_rel:
                continue
            tree = index.ast(rel)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in _CHANNEL_CLASSES
                ):
                    findings.append(
                        Finding(
                            PASS_ID, rel, node.lineno,
                            f"mirror-channel:{node.func.id}:{rel}",
                            f"{node.func.id} constructed outside {disp_rel} "
                            "— a private command channel is per-feature "
                            "mirror code; route the step through the "
                            "DispatchBackend protocol",
                        )
                    )
        return findings

    # -- 3. the one funnel --------------------------------------------------

    def _funnel(self, index: RepoIndex) -> list[Finding]:
        eng_rel = index.config["engine_module"]
        etree = index.ast(eng_rel)
        if etree is None:
            return []
        findings: list[Finding] = []
        for node in ast.walk(etree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _FUNNELS
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == "_backend"
            ):
                continue
            fn = _enclosing_function(node)
            allowed = _FUNNELS[node.func.attr]
            if fn != allowed:
                findings.append(
                    Finding(
                        PASS_ID, eng_rel, node.lineno,
                        f"emit-outside-funnel:{node.func.attr}:{fn or '<module>'}",
                        f"_backend.{node.func.attr} called from "
                        f"{fn or '<module level>'} — only {allowed!r} may "
                        "touch it; anything else desynchronizes the "
                        "leader/follower step order",
                    )
                )
        return findings
