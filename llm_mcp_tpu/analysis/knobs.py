"""Knob-registry pass: every TPU_*/LLM_MCP_TPU_* env read, accounted for.

The operator doc (doc/README.md) carries ~50 env rows maintained by hand
against readers scattered across four read idioms: `os.environ.get`,
`os.environ[...]`, the typed `getenv*` helpers in utils/config.py, and
the local `_env_int`/`_env_float` helpers the stdlib-pinned telemetry
modules keep so they don't import config. Rows drift — PR after PR added
knobs (TPU_TRACE, TPU_EMBED_QUANT, TPU_PREFILL_BUCKETS...) whose only
documentation was the reading module's docstring.

This pass extracts the registry from the AST — knob name, default (when
the read passes a literal), every reading site — and fails in both
directions:

- **undocumented**: a knob some code reads with no row in the doc's env
  tables. Fix: add the row (or baseline a deliberately internal knob).
- **dead-doc**: a doc row naming a knob no code reads. Fix: delete the
  row or restore the reader — a documented knob that does nothing is an
  operator trap (the DB_DSN lesson, utils/config.py).

Scan roots are the package plus `bench.py` and `scripts/` (doc rows like
BENCH_COLDSTART are read there); tests never count as reading sites. A
"doc row" is a markdown table row whose FIRST cell backticks the name —
prose mentions (e.g. "replaces the retired `TPU_PREFILL_BOOST`") do not
document a knob.

The full registry rides the `--json` report so future automation (config
dump endpoints, doc generators) can consume it without re-parsing.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .core import Finding, RepoIndex

PASS_ID = "knob-registry"

# callable names that read an env var with the var name as first argument
_READER_NAMES = {
    "get", "getenv", "getenv_int", "getenv_float", "getenv_bool",
    "pop", "setdefault",
}
_READER_PREFIXES = ("_env",)  # _env_int / _env_float / _env_bool helpers


@dataclass
class Knob:
    name: str
    sites: list[str] = field(default_factory=list)  # "path:line"
    defaults: list[str] = field(default_factory=list)  # literal 2nd args

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "sites": sorted(self.sites),
            "defaults": sorted(set(self.defaults)),
        }


def _is_reader(func: ast.expr) -> bool:
    name = None
    if isinstance(func, ast.Attribute):
        name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    if name is None:
        return False
    return name in _READER_NAMES or name.startswith(_READER_PREFIXES)


def extract_registry(index: RepoIndex) -> dict[str, Knob]:
    prefixes = tuple(index.config["knob_prefixes"])
    roots = [index.config["package"]] + list(
        index.config["knob_extra_roots"]
    )
    files: list[str] = []
    for r in roots:
        files.extend(index.files_under(r))
    knobs: dict[str, Knob] = {}

    def note(name: str, relpath: str, line: int, default: str | None):
        k = knobs.setdefault(name, Knob(name))
        k.sites.append(f"{relpath}:{line}")
        if default is not None:
            k.defaults.append(default)

    for relpath in files:
        tree = index.ast(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_reader(node.func):
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith(prefixes)
                ):
                    default = None
                    if len(node.args) > 1 and isinstance(
                        node.args[1], ast.Constant
                    ):
                        default = repr(node.args[1].value)
                    note(
                        node.args[0].value, relpath, node.lineno, default
                    )
            elif isinstance(node, ast.Subscript):
                base = node.value
                is_environ = (
                    isinstance(base, ast.Attribute)
                    and base.attr == "environ"
                ) or (isinstance(base, ast.Name) and base.id == "environ")
                if (
                    is_environ
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value.startswith(prefixes)
                    and isinstance(getattr(node, "ctx", None), ast.Load)
                ):
                    note(node.slice.value, relpath, node.lineno, None)
    return knobs


_ROW_CELL_RE = re.compile(r"^\|([^|]*)\|")
_TICKED_RE = re.compile(r"`([A-Z][A-Z0-9_]*)`")


def doc_rows(text: str, prefixes: tuple[str, ...]) -> dict[str, int]:
    """name -> first doc line for every knob named in the FIRST cell of a
    markdown table row (handles `A` / `B` twin rows)."""
    out: dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = _ROW_CELL_RE.match(line.strip())
        if not m:
            continue
        for name in _TICKED_RE.findall(m.group(1)):
            if name.startswith(prefixes):
                out.setdefault(name, lineno)
    return out


class KnobRegistryPass:
    pass_id = PASS_ID

    def run(self, index: RepoIndex) -> list[Finding]:
        prefixes = tuple(index.config["knob_prefixes"])
        doc_rel = index.config["doc_readme"]
        text = index.text(doc_rel)
        if text is None:
            return [
                Finding(
                    PASS_ID, doc_rel, 0, "doc-missing",
                    f"{doc_rel} not found — the env catalog must exist",
                )
            ]
        registry = extract_registry(index)
        documented = doc_rows(text, prefixes)
        findings: list[Finding] = []
        for name, knob in sorted(registry.items()):
            if name not in documented:
                site = sorted(knob.sites)[0]
                path, _, line = site.rpartition(":")
                findings.append(
                    Finding(
                        PASS_ID, path, int(line),
                        f"undocumented:{name}",
                        f"env knob {name} is read at {len(knob.sites)} "
                        f"site(s) (first: {site}) but has no row in "
                        f"{doc_rel} — document it or baseline it as "
                        "internal",
                    )
                )
        for name, line in sorted(documented.items()):
            if name not in registry:
                findings.append(
                    Finding(
                        PASS_ID, doc_rel, line,
                        f"dead-doc:{name}",
                        f"{doc_rel}:{line} documents env knob {name} that "
                        "no code reads — delete the row or restore the "
                        "reader",
                    )
                )
        return findings


def registry_json(index: RepoIndex) -> list[dict]:
    """Stable-ordered registry for the --json report."""
    return [
        k.to_dict() for _, k in sorted(extract_registry(index).items())
    ]
