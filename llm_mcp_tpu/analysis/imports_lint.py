"""Import-purity pass: the stdlib-only module pins, single-sourced.

Five modules are deliberately importable without jax (and mostly without
numpy): the flight recorder, tracer, and perf observatory (every layer
imports telemetry, so telemetry must weigh nothing), the migration wire
codec (CPU-only worker hosts decode and forward payloads), and the
n-gram drafter (runs on the host thread and inside follower processes).
Each pin used to live as a hand-rolled subprocess test in a different
test file with its own stub-package boilerplate; PURITY_MANIFEST below
is the one declarative statement of all of them, consumed twice:

- **statically** (this pass): module-level imports of each pinned module
  must resolve to stdlib + the entry's `allow` set. Lazy imports inside
  functions are fine — that is the sanctioned escape hatch (config.py's
  jax import, engine hooks) — so the check walks only code that executes
  at import time.
- **at runtime** (`run_probe`, called by the thin tier-1 tests): the
  module is loaded by file path in a subprocess with stubbed parent
  packages, its `exercise` snippet runs the happy path, and sys.modules
  must contain nothing matching the entry's `forbidden` prefixes. This
  catches what static analysis cannot: a *stdlib* import whose module
  transitively drags in a forbidden one, or an exercise path that calls
  a lazy import.

Adding a pin = adding a manifest entry; both checks pick it up.
"""

from __future__ import annotations

import ast
import subprocess
import sys
import textwrap
from dataclasses import dataclass, field

from .core import Finding, RepoIndex

PASS_ID = "import-purity"


@dataclass
class PurityEntry:
    key: str
    path: str  # repo-relative module path
    # import-name prefixes allowed beyond stdlib at module level
    allow: tuple[str, ...] = ()
    # sys.modules prefixes that must be absent after the runtime probe
    forbidden: tuple[str, ...] = ("jax", "numpy")
    # parent packages to stub before loading by file path
    stubs: tuple[str, ...] = ()
    # extra modules to load (by file path, in order) before the module
    deps: tuple[str, ...] = ()
    # runtime snippet exercising the module (it is bound as `mod`);
    # {tmp} substitutes a scratch dir when the test passes one
    exercise: str = ""
    why: str = ""


PURITY_MANIFEST: tuple[PurityEntry, ...] = (
    PurityEntry(
        key="recorder",
        path="llm_mcp_tpu/telemetry/recorder.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.telemetry"),
        forbidden=(
            "llm_mcp_tpu.executor", "llm_mcp_tpu.api",
            "llm_mcp_tpu.routing", "llm_mcp_tpu.worker",
            "llm_mcp_tpu.rpc", "jax", "numpy",
        ),
        exercise=textwrap.dedent(
            """
            import json
            rec = mod.FlightRecorder(capacity=16, dump_dir={tmp!r},
                                     dump_interval_s=0.0)
            rec.event("decode", trace_id="a" * 32, rows=1)
            path = rec.dump("lint", force=True)
            rows = [json.loads(l) for l in open(path)]
            assert rows[0]["kind"] == "flight_dump"
            assert rows[1]["etype"] == "decode"
            """
        ),
        why="journals the hot path from every layer; must weigh nothing",
    ),
    PurityEntry(
        key="tracing",
        path="llm_mcp_tpu/telemetry/tracing.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.telemetry"),
        forbidden=(
            "llm_mcp_tpu.executor", "llm_mcp_tpu.api",
            "llm_mcp_tpu.routing", "llm_mcp_tpu.worker",
            "llm_mcp_tpu.rpc", "jax", "numpy",
        ),
        exercise=textwrap.dedent(
            """
            tr = mod.Tracer(max_traces=8)
            with tr.span("api") as sp:
                pass
            assert tr.get_trace(sp.trace_id), "span did not record"
            """
        ),
        why="every request path carries a trace; imported by all layers",
    ),
    PurityEntry(
        key="perf",
        path="llm_mcp_tpu/telemetry/perf.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.telemetry"),
        forbidden=(
            "llm_mcp_tpu.executor", "llm_mcp_tpu.api",
            "llm_mcp_tpu.models", "llm_mcp_tpu.worker",
            "llm_mcp_tpu.rpc", "jax", "numpy",
        ),
        exercise=textwrap.dedent(
            """
            shape = mod.ModelShape(dim=64, n_layers=2, n_heads=4,
                                   n_kv_heads=2, head_dim=16,
                                   param_count=1000)
            obs = mod.PerfObservatory(shape)
            obs.observe_itl(0.1, 2)
            obs.finish_request(10.0, 5.0, 8)
            obs.should_sample("decode")
            obs.observe_phase("decode", 0.001, 0.01, tokens=8, rows=2,
                              ctx_mean=32.0)
            st = obs.stats()
            assert set(st["roofline"]["layouts"]) == set(mod.CACHE_LAYOUTS)
            """
        ),
        why="cost models + rooflines sampled from the engine loop",
    ),
    PurityEntry(
        key="workload",
        path="llm_mcp_tpu/telemetry/workload.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.telemetry"),
        forbidden=(
            "llm_mcp_tpu.executor", "llm_mcp_tpu.api",
            "llm_mcp_tpu.routing", "llm_mcp_tpu.worker",
            "llm_mcp_tpu.rpc", "jax", "numpy",
        ),
        exercise=textwrap.dedent(
            """
            import os
            wl = mod.WorkloadTrace(capacity=16, trace_path="",
                                   include_ids=True)
            rec = wl.record(ts=1.0, rid="r1", prompt_tokens=4,
                            chain=[(4, "aa")], max_tokens=2,
                            output_tokens=2, finish="length",
                            ids=[1, 2, 3, 4])
            assert rec is not None and rec["ids"] == [1, 2, 3, 4]
            path = os.path.join({tmp!r}, "wl.jsonl")
            assert wl.dump(path) == 1
            recs, rej = mod.parse_trace(open(path).read().splitlines()
                                        + ["garbage"])
            assert len(recs) == 1 and rej == 1
            assert mod.synth_trace("agent", 4, seed=1) == \\
                mod.synth_trace("agent", 4, seed=1)
            wf = mod.LatencyWaterfall(window=8)
            wf.observe({{"decode": 0.5, "prefill_compute": 0.5}}, 1.0)
            assert wf.stats()["coverage"] == 1.0
            """
        ),
        why="capture ring + waterfall ledger fed from the decode hot path",
    ),
    PurityEntry(
        key="migration",
        path="llm_mcp_tpu/executor/migration.py",
        allow=("numpy", "llm_mcp_tpu.utils.locks",
               "llm_mcp_tpu.executor.memory"),
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.utils", "llm_mcp_tpu.executor"),
        deps=("llm_mcp_tpu/utils/locks.py", "llm_mcp_tpu/executor/memory.py"),
        forbidden=("jax", "grpc"),
        exercise=textwrap.dedent(
            """
            import numpy as np
            h, t = mod.decode_payload(mod.encode_payload(
                {{"x": 1}}, {{"k": np.ones((1, 1, 1, 2, 1), np.float32)}}))
            assert h == {{"x": 1}} and t["k"].shape == (1, 1, 1, 2, 1)
            """
        ),
        why="wire codec must run on CPU-only worker hosts (stdlib+numpy)",
    ),
    PurityEntry(
        key="memory",
        path="llm_mcp_tpu/executor/memory.py",
        allow=("llm_mcp_tpu.utils.locks",),
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.utils", "llm_mcp_tpu.executor"),
        deps=("llm_mcp_tpu/utils/locks.py",),
        forbidden=("jax", "grpc", "numpy"),
        exercise=textwrap.dedent(
            """
            pool = mod.KVPool(max_slots=2, max_seq_len=8, bytes_per_slot=64)
            assert pool.admit_ok(0.0) and pool.hbm_bytes() == 128
            """
        ),
        why="host-side HBM bookkeeping imported by the migration codec",
    ),
    PurityEntry(
        key="drafter",
        path="llm_mcp_tpu/executor/drafter.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.executor"),
        forbidden=("jax", "numpy"),
        exercise=textwrap.dedent(
            """
            assert mod.NGramDrafter(2, 3).draft(4) == []
            """
        ),
        why="runs on the engine host thread and in follower processes",
    ),
    PurityEntry(
        key="cn-grammar",
        path="llm_mcp_tpu/constrain/grammar.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.constrain"),
        forbidden=("jax", "numpy"),
        exercise=textwrap.dedent(
            """
            rules, start = mod.regex_to_grammar("a(b|c){{2}}")
            a = mod.ByteAutomaton(rules, start)
            s = a.step_bytes(a.start_state, b"abc")
            assert s >= 0 and a.accepting(s)
            assert a.step(a.start_state, ord("z")) == -1
            """
        ),
        why="constraint compilation runs on API + engine host threads",
    ),
    PurityEntry(
        key="cn-masks",
        path="llm_mcp_tpu/constrain/masks.py",
        allow=("numpy", "llm_mcp_tpu.constrain.grammar",
               "llm_mcp_tpu.constrain.schema"),
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.constrain"),
        deps=("llm_mcp_tpu/constrain/grammar.py",
              "llm_mcp_tpu/constrain/schema.py"),
        forbidden=("jax", "llm_mcp_tpu.executor", "llm_mcp_tpu.api"),
        exercise=textwrap.dedent(
            """
            class Tok:
                vocab_size = 259
                pad_id, bos_id, eos_id = 0, 1, 2
                OFFSET = 3
            cc = mod.ConstraintCompiler(Tok(), 259, cache_size=2)
            sa = cc.make({{"type": "choice", "choices": ["ab", "cd"]}})
            legal = [t for t in range(259) if sa.allows(t)]
            assert legal == [3 + ord("a"), 3 + ord("c")], legal
            assert sa.advance(3 + ord("a")) and sa.advance(3 + ord("b"))
            assert sa.accepting and sa.allows(2)
            assert cc.stats()["misses"] == 1
            """
        ),
        why="mask lift is host-only; the device sees packed words alone",
    ),
    PurityEntry(
        key="locks",
        path="llm_mcp_tpu/utils/locks.py",
        stubs=("llm_mcp_tpu", "llm_mcp_tpu.utils"),
        forbidden=("jax", "numpy", "grpc"),
        exercise=textwrap.dedent(
            """
            lo = mod.OrderedLock("a", 1)
            hi = mod.OrderedLock("b", 2)
            with lo:
                with hi:
                    pass
            try:
                with hi:
                    with lo:
                        raise AssertionError("rank check dead")
            except mod.LockOrderError:
                pass
            """
        ),
        why="the rank discipline itself must import nothing",
    ),
)


def manifest_entry(key: str) -> PurityEntry:
    for e in PURITY_MANIFEST:
        if e.key == key:
            return e
    raise KeyError(f"no purity-manifest entry {key!r}")


# -- static half -------------------------------------------------------------


def _module_level_imports(tree: ast.Module) -> list[tuple[str, int]]:
    """(absolute-ish import name, line) for imports that execute at module
    import time — module body plus any non-function nesting (if/try)."""
    out: list[tuple[str, int]] = []

    def visit(body, in_func: bool):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Import):
                out.extend((a.name, node.lineno) for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                out.append((node.module or "", node.lineno))
                # relative level recorded by caller via marker
                if node.level:
                    out[-1] = (f"{'.' * node.level}{node.module or ''}",
                               node.lineno)
            elif isinstance(node, (ast.If, ast.Try, ast.With)):
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, attr, [])
                    for s in sub:
                        if isinstance(s, ast.ExceptHandler):
                            visit(s.body, in_func)
                    if sub and not isinstance(sub[0], ast.ExceptHandler):
                        visit(sub, in_func)
            elif isinstance(node, ast.ClassDef):
                visit(node.body, in_func)

    visit(tree.body, False)
    return out


def _absolutize(name: str, module_relpath: str) -> str:
    """Resolve a leading-dots relative import against the module's
    package path (llm_mcp_tpu/executor/migration.py + '..utils.locks'
    -> llm_mcp_tpu.utils.locks)."""
    if not name.startswith("."):
        return name
    level = len(name) - len(name.lstrip("."))
    pkg_parts = module_relpath.replace("\\", "/").split("/")[:-1]
    base = pkg_parts[: len(pkg_parts) - (level - 1)]
    tail = name.lstrip(".")
    return ".".join(base + ([tail] if tail else []))


def _stdlib_names() -> frozenset[str]:
    return getattr(sys, "stdlib_module_names", frozenset())


class ImportPurityPass:
    pass_id = PASS_ID

    def __init__(self, manifest: tuple[PurityEntry, ...] = PURITY_MANIFEST):
        self.manifest = manifest

    def run(self, index: RepoIndex) -> list[Finding]:
        findings: list[Finding] = []
        stdlib = _stdlib_names()
        for entry in self.manifest:
            tree = index.ast(entry.path)
            if tree is None:
                findings.append(
                    Finding(
                        PASS_ID, entry.path, 0,
                        f"pinned-module-missing:{entry.key}",
                        f"purity-pinned module {entry.path} "
                        f"({entry.key}) does not exist — update "
                        "PURITY_MANIFEST",
                    )
                )
                continue
            for name, line in _module_level_imports(tree):
                absname = _absolutize(name, entry.path)
                top = absname.split(".")[0]
                if top == "__future__" or top in stdlib:
                    continue
                if any(
                    absname == a or absname.startswith(a + ".")
                    or a.startswith(absname + ".")
                    for a in entry.allow
                ):
                    continue
                findings.append(
                    Finding(
                        PASS_ID, entry.path, line,
                        f"impure-import:{entry.key}:{absname}",
                        f"{entry.path} is pinned "
                        f"{'stdlib-only' if not entry.allow else 'to stdlib + ' + ', '.join(entry.allow)}"
                        f" ({entry.why}) but imports {absname!r} at module "
                        "level — make it lazy or amend the manifest",
                    )
                )
        return findings


# -- runtime half (called by the thin tier-1 tests) --------------------------

_PROBE_TEMPLATE = """
import importlib.util, sys, types
for pkg in {stubs!r}:
    m = types.ModuleType(pkg)
    m.__path__ = []
    sys.modules[pkg] = m
mod = None
for name, path in {loads!r}:
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
{exercise}
bad = sorted(m for m in sys.modules if m.startswith({forbidden!r}))
sys.exit("%s pulled in: %s" % ({key!r}, bad) if bad else 0)
"""


def probe_code(key: str, repo_root: str, tmp: str = "") -> str:
    """The subprocess source for a manifest entry's runtime probe."""
    import os

    entry = manifest_entry(key)

    def modname(relpath: str) -> str:
        return relpath[:-3].replace("/", ".")

    loads = [
        (modname(dep), os.path.join(repo_root, dep)) for dep in entry.deps
    ]
    loads.append((modname(entry.path), os.path.join(repo_root, entry.path)))
    exercise = textwrap.indent(
        entry.exercise.format(tmp=tmp).strip(), ""
    )
    return _PROBE_TEMPLATE.format(
        stubs=tuple(entry.stubs),
        loads=loads,
        exercise=exercise,
        forbidden=tuple(entry.forbidden),
        key=key,
    )


def run_probe(
    key: str, repo_root: str, tmp: str = "", timeout: float = 120.0
) -> subprocess.CompletedProcess:
    """Run a manifest entry's runtime import probe in a subprocess.

    Returns the CompletedProcess; rc 0 means the module loaded by file
    path, passed its exercise snippet, and pulled in nothing forbidden."""
    return subprocess.run(
        [sys.executable, "-c", probe_code(key, repo_root, tmp)],
        capture_output=True, text=True, timeout=timeout,
    )
