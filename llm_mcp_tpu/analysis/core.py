"""llmtpu-lint core: the pass framework every analyzer plugs into.

The repo's correctness story leaned on runtime checks (OrderedLock rank
raises, the KERNEL_PARITY guard test, per-module subprocess import lints)
re-invented ad hoc in four test files. This package is the `go vet` the
Python/JAX rewrite never had: a shared AST/module index over the package,
a `Finding` type with a stable fingerprint (pass id + symbolic key, NO
line numbers — findings survive unrelated edits), an allowlist baseline so
only *new* violations fail, and a suite runner that every entry point
(`python -m llm_mcp_tpu.analysis`, `scripts/lint_gate.py`, the tier-1
test in tests/test_analysis.py) shares.

Design rules for passes:

- **AST only, never import.** A pass must never import the module it
  inspects — half the package pulls jax at import time, and the suite has
  to run on a proxy-only worker host in under 30 s. Anything a pass needs
  from a module (registry tuples, dict literals, docstrings) is extracted
  from the parse tree via the `literal_assignment` helpers here.
- **Symbolic keys.** A finding's `key` names the violation, not its
  coordinates: `nest:kvpool<-engine.stats@KVPool.admit`, not a line
  number. The baseline matches on `(pass_id, key)` so a baselined entry
  stays matched across reformatting, and a *moved* violation is still the
  same violation.
- **Config over hardcoding.** Every repo path a pass touches comes from
  `RepoIndex.config` (DEFAULT_CONFIG below) so tests can point a pass at
  fixture snippets in tmp dirs and assert it fires exactly once.
"""

from __future__ import annotations

import ast
import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable

# Every path is repo-root-relative with forward slashes (normalized in
# RepoIndex.rel) so fingerprints are stable across platforms.
DEFAULT_CONFIG: dict = {
    # the package the suite walks
    "package": "llm_mcp_tpu",
    # documentation inputs
    "doc_readme": "doc/README.md",
    "doc_concurrency": "doc/concurrency.md",
    # registry-census inputs
    "kernel_module": "llm_mcp_tpu/kernels/attention.py",
    "parity_registry": "tests/test_kernel_parity.py",
    "engine_module": "llm_mcp_tpu/executor/engine.py",
    "dispatch_module": "llm_mcp_tpu/executor/dispatch.py",
    "zoo_module": "llm_mcp_tpu/executor/zoo.py",
    "perf_module": "llm_mcp_tpu/telemetry/perf.py",
    "recorder_module": "llm_mcp_tpu/telemetry/recorder.py",
    # knob-registry scan: the package plus the out-of-package readers the
    # operator doc documents (bench.py's BENCH_* rows ride along)
    "knob_extra_roots": ["bench.py", "scripts"],
    "knob_prefixes": ("TPU_", "LLM_MCP_TPU_"),
    # etypes the recorder census must explicitly list even if the engine
    # stops emitting them (tests/test_perf.py pinned these; wl/wf are the
    # workload-capture and latency-waterfall marks from telemetry/workload;
    # zoo/swap_in/swap_out are the model-zoo residency trail from
    # executor/zoo.py; cn_cmp/cnstep/cn_spec are the grammar-constrained
    # decoding trail from llm_mcp_tpu/constrain + the engine cn rounds)
    "required_etypes": (
        "pf_rag", "fused_rag", "perf", "wl", "wf",
        "zoo", "swap_in", "swap_out",
        "cn_cmp", "cnstep", "cn_spec",
    ),
}

BASELINE_PATH = "llm_mcp_tpu/analysis/baseline.txt"


@dataclass(frozen=True)
class Finding:
    """One violation: where it is and — via `key` — *what* it is.

    `path`/`line` are for humans and editors; `fingerprint` (pass_id +
    key) is what the baseline and the gate match on.
    """

    pass_id: str
    path: str
    line: int
    key: str
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.pass_id}::{self.key}"

    def to_dict(self) -> dict:
        return {
            "pass": self.pass_id,
            "path": self.path,
            "line": self.line,
            "key": self.key,
            "message": self.message,
        }


class RepoIndex:
    """Shared parse-once AST loader over the repo tree.

    Passes ask for files by repo-relative path; parse results are cached
    so the five passes re-reading engine.py cost one parse. Missing files
    return None — a pass decides whether that is a finding (a registry
    moved) or a skip (an optional doc)."""

    def __init__(self, root: str, config: dict | None = None):
        self.root = os.path.abspath(root)
        self.config = dict(DEFAULT_CONFIG)
        if config:
            self.config.update(config)
        self._ast_cache: dict[str, ast.Module | None] = {}
        self._text_cache: dict[str, str | None] = {}
        self.parse_errors: list[Finding] = []

    # -- file access -------------------------------------------------------

    def rel(self, path: str) -> str:
        return os.path.relpath(path, self.root).replace(os.sep, "/")

    def abspath(self, relpath: str) -> str:
        return os.path.join(self.root, relpath.replace("/", os.sep))

    def exists(self, relpath: str) -> bool:
        return os.path.isfile(self.abspath(relpath))

    def text(self, relpath: str) -> str | None:
        if relpath not in self._text_cache:
            try:
                with open(self.abspath(relpath), encoding="utf-8") as fh:
                    self._text_cache[relpath] = fh.read()
            except OSError:
                self._text_cache[relpath] = None
        return self._text_cache[relpath]

    def ast(self, relpath: str) -> ast.Module | None:
        if relpath not in self._ast_cache:
            src = self.text(relpath)
            if src is None:
                self._ast_cache[relpath] = None
            else:
                try:
                    tree = ast.parse(src)
                    attach_parents(tree)
                    self._ast_cache[relpath] = tree
                except SyntaxError as exc:
                    self._ast_cache[relpath] = None
                    self.parse_errors.append(
                        Finding(
                            "framework", relpath, exc.lineno or 0,
                            f"syntax:{relpath}",
                            f"unparseable module: {exc.msg}",
                        )
                    )
        return self._ast_cache[relpath]

    # -- tree walks --------------------------------------------------------

    def package_files(self) -> list[str]:
        """Sorted repo-relative paths of every .py file in the package."""
        return self.files_under(self.config["package"])

    def files_under(self, relpath: str) -> list[str]:
        top = self.abspath(relpath)
        if os.path.isfile(top):
            return [relpath] if relpath.endswith(".py") else []
        out: list[str] = []
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(self.rel(os.path.join(dirpath, fn)))
        return sorted(out)


# -- AST extraction helpers shared by passes --------------------------------


def attach_parents(tree: ast.Module) -> None:
    """Thread `_lint_parent` links through the tree (ast has no parent
    pointers); RepoIndex does this on every parse so passes can walk up."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._lint_parent = node  # type: ignore[attr-defined]


def literal_assignment(tree: ast.Module, name: str) -> ast.expr | None:
    """The value expression of a module-level `name = <expr>` assignment
    (last one wins, matching runtime semantics)."""
    found: ast.expr | None = None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    found = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == name:
                found = node.value
    return found


def string_tuple(tree: ast.Module, name: str) -> list[str] | None:
    """A module-level tuple/list-of-strings assignment, e.g.
    DISPATCH_PHASES."""
    expr = literal_assignment(tree, name)
    if not isinstance(expr, (ast.Tuple, ast.List)):
        return None
    out = []
    for elt in expr.elts:
        if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
            return None
        out.append(elt.value)
    return out


def dict_string_keys(tree: ast.Module, name: str) -> list[str] | None:
    """String keys of a module-level dict literal (values may be anything,
    including lambdas — PHASE_COSTS)."""
    expr = literal_assignment(tree, name)
    if not isinstance(expr, ast.Dict):
        return None
    out = []
    for k in expr.keys:
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        out.append(k.value)
    return out


def int_constants(tree: ast.Module) -> dict[str, int]:
    """Module-level NAME = <int literal> bindings — enough to resolve
    `rank=MIGRATION_LOCK_RANK`-style indirection without importing."""
    out: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if (
                isinstance(tgt, ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)
            ):
                out[tgt.id] = node.value.value
    return out


def call_string_args(
    tree: ast.Module, attr_names: Iterable[str]
) -> dict[str, set[str]]:
    """First-argument string constants of every `<something>.name("...")`
    call, per name — the engine-side half of the registry censuses
    (`_compile_obs`, `_note_exec_shape`, `event`)."""
    out: dict[str, set[str]] = {a: set() for a in attr_names}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in out
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out[node.func.attr].add(node.args[0].value)
    return out


def walk_skipping_functions(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a tree but do not descend into function/lambda bodies — the
    shape of "executed at import time"."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


# -- baseline ----------------------------------------------------------------
#
# Format: one finding per line, `pass_id<spaces>key  # justification`.
# The justification comment is MANDATORY — a baseline entry is a decision,
# and decisions get written down. `parse_baseline` rejects bare entries so
# the file can't silently absorb violations.


@dataclass
class BaselineEntry:
    pass_id: str
    key: str
    justification: str
    line: int

    @property
    def fingerprint(self) -> str:
        return f"{self.pass_id}::{self.key}"


def parse_baseline(text: str, path: str = BASELINE_PATH) -> list[BaselineEntry]:
    entries: list[BaselineEntry] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        body, _, comment = line.partition("#")
        fields = body.split()
        if len(fields) != 2 or not comment.strip():
            raise ValueError(
                f"{path}:{lineno}: baseline entries are "
                f"'pass_id key  # justification' (justification required); "
                f"got {raw!r}"
            )
        entries.append(
            BaselineEntry(fields[0], fields[1], comment.strip(), lineno)
        )
    return entries


# -- suite -------------------------------------------------------------------


@dataclass
class PassResult:
    pass_id: str
    findings: list[Finding]
    seconds: float


@dataclass
class SuiteResult:
    results: list[PassResult]
    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    stale_baseline: list[BaselineEntry] = field(default_factory=list)
    baseline_error: str | None = None
    seconds: float = 0.0

    @property
    def findings(self) -> list[Finding]:
        return [f for r in self.results for f in r.findings]

    @property
    def ok(self) -> bool:
        return not self.new and self.baseline_error is None

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "seconds": round(self.seconds, 3),
            "passes": [
                {
                    "pass": r.pass_id,
                    "findings": len(r.findings),
                    "seconds": round(r.seconds, 3),
                }
                for r in self.results
            ],
            "new": [f.to_dict() for f in self.new],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": [
                {"pass": e.pass_id, "key": e.key, "line": e.line}
                for e in self.stale_baseline
            ],
            "baseline_error": self.baseline_error,
        }


def default_passes() -> list:
    """The six registered passes, in report order. Imported lazily so
    `core` stays importable from any of them."""
    from . import census, dispatch_surface, donation, imports_lint, knobs, lock_order

    return [
        lock_order.LockOrderPass(),
        donation.DonationSafetyPass(),
        knobs.KnobRegistryPass(),
        imports_lint.ImportPurityPass(),
        census.RegistryCensusPass(),
        dispatch_surface.DispatchSurfacePass(),
    ]


def run_suite(
    root: str,
    passes: list | None = None,
    config: dict | None = None,
    baseline_text: str | None = None,
) -> SuiteResult:
    """Run the passes over `root`, split findings into new vs baselined.

    `baseline_text=None` loads the committed baseline file (missing file
    == empty baseline); pass `""` to run baseline-free."""
    index = RepoIndex(root, config)
    results: list[PassResult] = []
    t_suite = time.monotonic()
    for p in passes if passes is not None else default_passes():
        t0 = time.monotonic()
        found = sorted(
            p.run(index), key=lambda f: (f.path, f.line, f.key)
        )
        results.append(PassResult(p.pass_id, found, time.monotonic() - t0))
    if index.parse_errors:
        results.insert(
            0, PassResult("framework", list(index.parse_errors), 0.0)
        )

    out = SuiteResult(results)
    if baseline_text is None:
        baseline_text = index.text(BASELINE_PATH) or ""
    try:
        entries = parse_baseline(baseline_text)
    except ValueError as exc:
        out.baseline_error = str(exc)
        entries = []
    allow = {e.fingerprint: e for e in entries}
    seen: set[str] = set()
    for f in out.findings:
        if f.fingerprint in allow:
            out.baselined.append(f)
            seen.add(f.fingerprint)
        else:
            out.new.append(f)
    out.stale_baseline = [e for e in entries if e.fingerprint not in seen]
    out.seconds = time.monotonic() - t_suite
    return out


def render_report(result: SuiteResult, json_mode: bool = False) -> str:
    if json_mode:
        return json.dumps(result.to_dict(), indent=2, sort_keys=True)
    lines: list[str] = []
    for r in result.results:
        lines.append(
            f"[{r.pass_id}] {len(r.findings)} finding(s) "
            f"({r.seconds * 1000:.0f} ms)"
        )
    if result.baseline_error:
        lines.append(f"BASELINE ERROR: {result.baseline_error}")
    for f in result.new:
        lines.append(f"  NEW {f.pass_id} {f.path}:{f.line}: {f.message}")
        lines.append(f"      key: {f.key}")
    for f in result.baselined:
        lines.append(
            f"  baselined {f.pass_id} {f.path}:{f.line}: {f.key}"
        )
    for e in result.stale_baseline:
        lines.append(
            f"  stale-baseline {e.pass_id} {e.key} "
            f"(baseline.txt:{e.line} matches nothing — delete the entry)"
        )
    verdict = "OK" if result.ok else "FAIL"
    lines.append(
        f"{verdict}: {len(result.new)} new, {len(result.baselined)} "
        f"baselined, {len(result.stale_baseline)} stale baseline entr"
        f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
        f"in {result.seconds:.2f}s"
    )
    return "\n".join(lines)


# Typing convenience for passes (duck-typed: anything with pass_id + run).
PassFn = Callable[[RepoIndex], list[Finding]]
