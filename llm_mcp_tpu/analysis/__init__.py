"""llmtpu-lint: the repo-native static-analysis suite.

Five AST-only passes over the package — lock-order, donation-safety,
knob-registry, import-purity, registry-census — behind one runner with a
justified-allowlist baseline. Entry points:

- ``python -m llm_mcp_tpu.analysis`` (human report; ``--json`` for CI)
- ``scripts/lint_gate.py`` (CI gate, perf_gate.py conventions)
- ``tests/test_analysis.py`` (tier-1: zero non-baselined findings)

See doc/static_analysis.md for the pass catalog and baseline workflow.
This package imports nothing heavier than ``ast`` — it must stay
runnable on a CPU-only host in well under the 30 s budget.
"""

from .core import (
    BASELINE_PATH,
    DEFAULT_CONFIG,
    BaselineEntry,
    Finding,
    PassResult,
    RepoIndex,
    SuiteResult,
    default_passes,
    parse_baseline,
    render_report,
    run_suite,
)

__all__ = [
    "BASELINE_PATH",
    "DEFAULT_CONFIG",
    "BaselineEntry",
    "Finding",
    "PassResult",
    "RepoIndex",
    "SuiteResult",
    "default_passes",
    "parse_baseline",
    "render_report",
    "run_suite",
]
