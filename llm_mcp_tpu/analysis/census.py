"""Registry-census pass: the cross-module string registries, reconciled.

Three registries keep fast-moving string namespaces honest, and each had
its own hand-rolled guard in a different test file:

1. **kernel parity** — every `_*_kernel` Pallas function in
   kernels/attention.py must appear in the `KERNEL_PARITY` dict
   (tests/test_kernel_parity.py) pointing at a test that exists. The
   blocked q8 kernel once shipped with zero coverage; this census is
   what keeps that from recurring. The dict itself stays in the test
   file — next to the tests it names — and is read here via AST.
2. **dispatch phases** — every `_compile_obs("phase")` ledger call in
   the engine must name a phase registered in DISPATCH_PHASES or
   AUX_COMPILE_PHASES (telemetry/perf.py); every steady-state dispatch
   phase must actually reach the ledger, have a PHASE_COSTS cost model,
   and be observed by `_note_exec_shape`. An unregistered phase compiles
   and runs but is invisible to the perf observatory.
3. **flight etypes** — every `.event("etype")` string the engine emits
   must appear in the recorder module docstring's identifier census
   (the docstring doubles as the etype catalog that flight_dump.py
   renders from), and the ragged-prefill + perf etypes must stay listed.

All three read source via AST only — no test-module or engine import —
so the census runs in milliseconds without jax.
"""

from __future__ import annotations

import ast
import re

from .core import (
    Finding,
    RepoIndex,
    call_string_args,
    dict_string_keys,
    literal_assignment,
    string_tuple,
)

PASS_ID = "registry-census"

_IDENT_RE = re.compile(r"[a-z_][a-z0-9_]*")


def _parity_registry(tree: ast.Module) -> dict[str, tuple[str, str]] | None:
    """The KERNEL_PARITY literal: kernel name -> (test file, test name)."""
    node = literal_assignment(tree, "KERNEL_PARITY")
    if not isinstance(node, ast.Dict):
        return None
    out: dict[str, tuple[str, str]] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        if isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) == 2 and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in v.elts
        ):
            out[k.value] = (v.elts[0].value, v.elts[1].value)
    return out


def _function_names(tree: ast.Module) -> set[str]:
    return {
        n.name
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


class RegistryCensusPass:
    pass_id = PASS_ID

    def run(self, index: RepoIndex) -> list[Finding]:
        findings: list[Finding] = []
        findings.extend(self._kernel_parity(index))
        findings.extend(self._dispatch_phases(index))
        findings.extend(self._flight_etypes(index))
        return findings

    # -- 1. kernel parity ----------------------------------------------------

    def _kernel_parity(self, index: RepoIndex) -> list[Finding]:
        kmod_rel = index.config["kernel_module"]
        reg_rel = index.config["parity_registry"]
        ktree = index.ast(kmod_rel)
        rtree = index.ast(reg_rel)
        if ktree is None or rtree is None:
            missing = kmod_rel if ktree is None else reg_rel
            return [
                Finding(
                    PASS_ID, missing, 0, "parity-file-missing",
                    f"{missing} not found — kernel-parity census cannot run",
                )
            ]
        registry = _parity_registry(rtree)
        if registry is None:
            return [
                Finding(
                    PASS_ID, reg_rel, 0, "parity-registry-missing",
                    f"no KERNEL_PARITY dict literal in {reg_rel}",
                )
            ]
        kernels = {
            n for n in _function_names(ktree)
            if n.startswith("_") and n.endswith("_kernel")
        }
        findings: list[Finding] = []
        if not kernels:
            findings.append(
                Finding(
                    PASS_ID, kmod_rel, 0, "no-kernels-found",
                    f"found no `_*_kernel` functions in {kmod_rel} — did "
                    "the naming convention change?",
                )
            )
        for name in sorted(kernels - set(registry)):
            findings.append(
                Finding(
                    PASS_ID, kmod_rel, 0, f"kernel-unregistered:{name}",
                    f"Pallas kernel {name} has no KERNEL_PARITY entry — add "
                    "an interpret-mode parity test and register it in "
                    f"{reg_rel}",
                )
            )
        for name in sorted(set(registry) - kernels):
            findings.append(
                Finding(
                    PASS_ID, reg_rel, 0, f"kernel-stale:{name}",
                    f"KERNEL_PARITY entry {name} names a kernel that no "
                    f"longer exists in {kmod_rel}",
                )
            )
        test_trees: dict[str, ast.Module | None] = {}
        for name, (mod_path, test_name) in sorted(registry.items()):
            if mod_path not in test_trees:
                test_trees[mod_path] = index.ast(mod_path)
            ttree = test_trees[mod_path]
            if ttree is None:
                findings.append(
                    Finding(
                        PASS_ID, reg_rel, 0,
                        f"parity-test-file-missing:{name}",
                        f"{name}: registered parity file {mod_path} does "
                        "not exist",
                    )
                )
            elif test_name not in _function_names(ttree):
                findings.append(
                    Finding(
                        PASS_ID, reg_rel, 0,
                        f"parity-test-missing:{name}",
                        f"{name}: registered test {mod_path}::{test_name} "
                        "does not exist",
                    )
                )
        return findings

    # -- 2. dispatch phases --------------------------------------------------

    def _dispatch_phases(self, index: RepoIndex) -> list[Finding]:
        perf_rel = index.config["perf_module"]
        eng_rel = index.config["engine_module"]
        ptree = index.ast(perf_rel)
        etree = index.ast(eng_rel)
        if ptree is None or etree is None:
            missing = perf_rel if ptree is None else eng_rel
            return [
                Finding(
                    PASS_ID, missing, 0, "phase-file-missing",
                    f"{missing} not found — dispatch-phase census cannot "
                    "run",
                )
            ]
        dispatch = string_tuple(ptree, "DISPATCH_PHASES")
        aux = string_tuple(ptree, "AUX_COMPILE_PHASES")
        costs = dict_string_keys(ptree, "PHASE_COSTS")
        if dispatch is None or aux is None or costs is None:
            gone = [
                n for n, v in (
                    ("DISPATCH_PHASES", dispatch),
                    ("AUX_COMPILE_PHASES", aux),
                    ("PHASE_COSTS", costs),
                )
                if v is None
            ]
            return [
                Finding(
                    PASS_ID, perf_rel, 0, "phase-registry-missing",
                    f"{perf_rel} no longer defines {', '.join(gone)} as "
                    "literals — the phase registry must stay statically "
                    "extractable",
                )
            ]
        got = call_string_args(etree, ("_compile_obs", "_note_exec_shape"))
        registered = set(dispatch) | set(aux)
        findings: list[Finding] = []
        for phase in sorted(got["_compile_obs"] - registered):
            findings.append(
                Finding(
                    PASS_ID, eng_rel, 0, f"phase-unregistered:{phase}",
                    f"engine ledgers compile phase {phase!r} that is in "
                    "neither DISPATCH_PHASES nor AUX_COMPILE_PHASES — the "
                    "observatory will never report it",
                )
            )
        for phase in sorted(set(dispatch) - got["_compile_obs"]):
            findings.append(
                Finding(
                    PASS_ID, perf_rel, 0, f"phase-unledgered:{phase}",
                    f"DISPATCH_PHASES entry {phase!r} never reaches "
                    "_compile_obs in the engine — dead registry row",
                )
            )
        for phase in sorted(set(dispatch) - set(costs)):
            findings.append(
                Finding(
                    PASS_ID, perf_rel, 0, f"phase-uncosted:{phase}",
                    f"dispatch phase {phase!r} has no PHASE_COSTS entry — "
                    "rooflines will misattribute its time",
                )
            )
        for phase in sorted(set(dispatch) - got["_note_exec_shape"]):
            findings.append(
                Finding(
                    PASS_ID, eng_rel, 0, f"phase-unsampled:{phase}",
                    f"dispatch phase {phase!r} is never passed to "
                    "_note_exec_shape — per-phase exec sampling misses it",
                )
            )
        return findings

    # -- 3. flight etypes ----------------------------------------------------

    def _flight_etypes(self, index: RepoIndex) -> list[Finding]:
        rec_rel = index.config["recorder_module"]
        eng_rel = index.config["engine_module"]
        rtree = index.ast(rec_rel)
        etree = index.ast(eng_rel)
        if rtree is None or etree is None:
            missing = rec_rel if rtree is None else eng_rel
            return [
                Finding(
                    PASS_ID, missing, 0, "etype-file-missing",
                    f"{missing} not found — etype census cannot run",
                )
            ]
        doc = ast.get_docstring(rtree) or ""
        census = set(_IDENT_RE.findall(doc))
        # the zoo joins the engine as an etype emitter (swap_in/swap_out/
        # zoo, executor/zoo.py) — its emissions face the same catalog
        emitters = [(eng_rel, etree)]
        zoo_rel = index.config.get("zoo_module", "")
        if zoo_rel:
            ztree = index.ast(zoo_rel)
            if ztree is not None:
                emitters.append((zoo_rel, ztree))
        findings: list[Finding] = []
        for mod_rel, mtree in emitters:
            emitted = call_string_args(mtree, ("event",))["event"]
            for etype in sorted(emitted - census):
                findings.append(
                    Finding(
                        PASS_ID, mod_rel, 0, f"etype-uncensused:{etype}",
                        f"{mod_rel} emits flight etype {etype!r} absent from "
                        f"the {rec_rel} docstring census — flight_dump.py "
                        "renders from that catalog; add the etype there",
                    )
                )
        for etype in sorted(
            set(index.config["required_etypes"]) - census
        ):
            findings.append(
                Finding(
                    PASS_ID, rec_rel, 0, f"etype-required-missing:{etype}",
                    f"required flight etype {etype!r} dropped from the "
                    f"{rec_rel} docstring census",
                )
            )
        return findings
