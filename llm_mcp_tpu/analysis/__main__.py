"""CLI for the static-analysis suite.

    python -m llm_mcp_tpu.analysis                 # human report, rc 1 on FAIL
    python -m llm_mcp_tpu.analysis --json          # machine report (stable v1)
    python -m llm_mcp_tpu.analysis --no-baseline   # show everything as new
    python -m llm_mcp_tpu.analysis --write-lock-table
        # regenerate the rank table between the markers in doc/concurrency.md
        # from the lock pass's extracted map (the doc can then never drift)

The --json payload carries the per-pass finding counts, new/baselined
findings with symbolic keys, the extracted env-knob registry, and the
lock rank map — everything scripts/lint_gate.py and future doc
generators need, versioned so consumers can pin."""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import lock_order
from .core import RepoIndex, render_report, run_suite
from .knobs import registry_json


def _repo_root() -> str:
    # llm_mcp_tpu/analysis/__main__.py -> repo root two levels up from pkg
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def write_lock_table(root: str) -> str:
    """Regenerate doc/concurrency.md's rank table between the markers.
    Returns the new table text; raises if the markers are missing."""
    index = RepoIndex(root)
    doc_rel = index.config["doc_concurrency"]
    text = index.text(doc_rel)
    if text is None:
        raise SystemExit(f"{doc_rel} not found under {root}")
    begin = text.find(lock_order.TABLE_BEGIN)
    end = text.find(lock_order.TABLE_END)
    if not (0 <= begin < end):
        raise SystemExit(
            f"{doc_rel} has no {lock_order.TABLE_BEGIN} ... "
            f"{lock_order.TABLE_END} marker block to regenerate"
        )
    ranks = lock_order.rank_map(index)
    defs, _ = lock_order.extract_lock_defs(index)
    where = {d.name: f"{d.path}:{d.line}" for d in defs}
    head = text[: text.index("\n", begin) + 1]  # keep the begin-marker line
    rows = ["| rank | lock | constructed at |", "| --- | --- | --- |"]
    for name, rank in sorted(ranks.items(), key=lambda kv: kv[1]):
        rows.append(f"| {rank} | `{name}` | `{where[name]}` |")
    table = "\n".join(rows)
    new = head + table + "\n" + text[end:]
    with open(
        index.abspath(doc_rel), "w", encoding="utf-8"
    ) as fh:
        fh.write(new)
    return table


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m llm_mcp_tpu.analysis",
        description="run the llmtpu-lint static-analysis suite",
    )
    ap.add_argument("--root", default=_repo_root(),
                    help="repo root (default: auto-detected)")
    ap.add_argument("--json", action="store_true", dest="json_mode",
                    help="machine-readable report on stdout")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore baseline.txt; every finding is new")
    ap.add_argument("--write-lock-table", action="store_true",
                    help="regenerate the doc/concurrency.md rank table "
                         "and exit")
    args = ap.parse_args(argv)

    if args.write_lock_table:
        table = write_lock_table(args.root)
        print(table)
        return 0

    result = run_suite(
        args.root, baseline_text="" if args.no_baseline else None
    )
    if args.json_mode:
        payload = result.to_dict()
        index = RepoIndex(args.root)
        payload["knob_registry"] = registry_json(index)
        payload["lock_ranks"] = lock_order.rank_map(index)
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(render_report(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
