"""Multi-host (DCN) bootstrap: jax.distributed + slice-aware global meshes.

The reference scales across hosts with NCCL-free plumbing — HTTP/gRPC +
Postgres + Tailscale (SURVEY.md §2.2 "Distributed communication backend").
This framework keeps that control plane for the CLUSTER (queue, discovery,
routing) and uses the TPU-native data plane for the MODEL: one
`jax.sharding.Mesh` spanning every chip of every host, with XLA inserting
ICI collectives inside a slice and DCN collectives across slices.

Boot order on a multi-host TPU pod / multi-slice deployment:

    from llm_mcp_tpu.parallel import distributed
    distributed.initialize()          # once per process, BEFORE first jax op
    mesh = distributed.make_global_mesh("dp=2,tp=8")

`initialize()` wraps `jax.distributed.initialize`, which on Cloud TPU VMs
auto-discovers the coordinator from the TPU metadata server; elsewhere it
reads the standard env triplet (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES
/ JAX_PROCESS_ID). Single-process runs skip cleanly, so the same serving
entrypoint works from a laptop to a pod.

`make_global_mesh` maps axes onto the physical fabric the way the scaling
book prescribes: the LEADING configured axis (usually `dp`, else `pp`) is
laid out across slices/hosts so its collectives (gradient-free at inference;
just independent batch shards) ride DCN, while `tp`/`sp` — whose collectives
are on the decode/prefill critical path — stay inside a slice on ICI.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXES, mesh_axis_sizes

log = logging.getLogger("distributed")

_initialized = False


def env_process_info() -> tuple[str, int, int] | None:
    """(coordinator, num_processes, process_id) from env, or None."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    if not addr:
        return None
    try:
        n = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
        pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
    except ValueError:
        return None
    return addr, n, pid


def initialize(force: bool = False) -> bool:
    """Idempotent `jax.distributed.initialize`. Returns True when a
    multi-process runtime was (or already is) initialized.

    - On Cloud TPU VMs with no env overrides, bare initialize() lets JAX
      read the TPU metadata server (worker count, coordinator).
    - Off-TPU, the JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID
      triplet drives it (the k8s manifests set these from the StatefulSet
      ordinal).
    - Single-process (no env, not a TPU pod): no-op, returns False.
    """
    global _initialized
    if _initialized and not force:
        return jax.process_count() > 1
    info = env_process_info()
    on_tpu_pod = bool(os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if info is None and not on_tpu_pod:
        log.debug("single-process run; jax.distributed not initialized")
        return False
    try:
        if info is not None:
            addr, n, pid = info
            jax.distributed.initialize(
                coordinator_address=addr, num_processes=n, process_id=pid
            )
        else:
            jax.distributed.initialize()
        _initialized = True
        log.info(
            "jax.distributed up: process %d/%d, %d global devices",
            jax.process_index(),
            jax.process_count(),
            len(jax.devices()),
        )
        return jax.process_count() > 1
    except Exception:
        log.exception("jax.distributed.initialize failed; continuing single-process")
        return False


def dcn_axis(sizes: dict[str, int]) -> str:
    """Which mesh axis should span slices/hosts (DCN): the first of dp/pp
    with size > 1 — their communication is off the per-token critical path.
    tp/sp collectives must stay on ICI."""
    for a in ("dp", "pp"):
        if sizes.get(a, 1) > 1:
            return a
    return ""


def make_global_mesh(spec: str = "") -> Mesh:
    """Build a mesh over ALL processes' devices, slice-topology-aware.

    With multiple slices (device.slice_index present and > 1 distinct), the
    DCN axis (dp/pp) is laid out across slices and the remaining axes within
    each slice, via mesh_utils.create_hybrid_device_mesh. Single-slice (or
    CPU test) runs reduce to the plain mesh — same axes, same semantics."""
    devices = jax.devices()
    sizes = mesh_axis_sizes(spec, len(devices))
    slice_ids = sorted({getattr(d, "slice_index", 0) for d in devices})
    n_slices = len(slice_ids)
    dcn = dcn_axis(sizes)

    if n_slices > 1 and dcn and sizes[dcn] % n_slices == 0:
        from jax.experimental import mesh_utils

        ici_sizes = dict(sizes)
        dcn_sizes = {a: 1 for a in AXES}
        dcn_sizes[dcn] = n_slices
        ici_sizes[dcn] = sizes[dcn] // n_slices
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=[ici_sizes[a] for a in AXES],
            dcn_mesh_shape=[dcn_sizes[a] for a in AXES],
            devices=devices,
        )
        log.info(
            "hybrid mesh: %s over %d slices (DCN axis %s)", sizes, n_slices, dcn
        )
        return Mesh(arr, axis_names=AXES)

    arr = np.asarray(devices).reshape(*(sizes[a] for a in AXES))
    return Mesh(arr, axis_names=AXES)


def host_local_batch(global_batch: int) -> int:
    """Slots this process feeds when the dp axis spans processes."""
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by {n} processes")
    return global_batch // n
