"""Parameter and KV-cache sharding specs (tensor parallelism).

Megatron-style TP mapping expressed as PartitionSpecs; XLA GSPMD inserts the
collectives:

  wq/wk/wv [L, D, H·hd]: shard output (head) dim on tp → per-chip heads
  wo       [L, H·hd, D]: shard input dim on tp → psum after projection
  w1/w3    [L, D, F]:    shard F on tp
  w2       [L, F, D]:    shard F on tp → psum after down-projection
  embed    [V, D]:       shard vocab on tp (vocab-parallel logits; top-k/argmax
                         over the sharded vocab axis gathers only [B, k])
  KV cache [L, B, Hkv, S, hd]: layers on pp, heads on tp, batch slots on dp

The stacked layer axis L shards on pp everywhere (params and cache): each
pipeline stage then holds only its own layers' weights and KV rows in HBM —
the capacity unlock pipeline_prefill's stage scan relies on. At pp=1 the
axis is a no-op and the specs reduce to the pure-TP mapping above.

GQA note: Llama-3.1-8B has 8 KV heads — exactly one per chip on a v5e-8 TP
mesh; Q heads (32) shard 4-per-chip. No KV replication needed up to tp=8.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig


def llama_param_specs(cfg: ModelConfig) -> dict[str, Any]:
    if cfg.kv_lora_rank:
        # MLA (models/mla.py): heads live inside flat [.., D, H*(dn+dr)]
        # projections — tp shards the head-packed output axes; the shared
        # latent down-projection and its norm replicate (the latent is
        # per-token global state every head reads).
        attn: dict[str, Any] = {
            "attn_norm": P("pp", None),
            "wq_mla": P("pp", None, "tp"),
            "w_dkv": P("pp", None, None),
            "kv_norm": P("pp", None),
            "w_ukv": P("pp", None, "tp"),
            "wo_mla": P("pp", "tp", None),
            "ffn_norm": P("pp", None),
        }
        dense_ffn = {
            "w1": P("pp", None, "tp"),
            "w3": P("pp", None, "tp"),
            "w2": P("pp", "tp", None),
        }
        if cfg.n_experts:
            ffn: dict[str, Any] = {
                "router": P("pp", None, None),
                "w1e": P("pp", "ep", None, "tp"),
                "w3e": P("pp", "ep", None, "tp"),
                "w2e": P("pp", "ep", "tp", None),
            }
            if cfg.n_shared_experts:
                ffn.update(
                    {
                        "w1s": P("pp", None, "tp"),
                        "w3s": P("pp", None, "tp"),
                        "w2s": P("pp", "tp", None),
                    }
                )
        else:
            ffn = dense_ffn
        specs: dict[str, Any] = {
            "embed": P("tp", None),
            "layers": {**attn, **ffn},
            "final_norm": P(None),
        }
        if cfg.n_experts and cfg.first_dense_layers:
            specs["dense_layers"] = {**attn, **dense_ffn}
        if not cfg.tie_embeddings:
            specs["lm_head"] = P(None, "tp")
        return specs
    layers: dict[str, Any] = {
        "attn_norm": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "ffn_norm": P("pp", None),
    }
    if cfg.qkv_bias:
        # biases follow their projection's output sharding
        layers.update({"bq": P("pp", "tp"), "bk": P("pp", "tp"), "bv": P("pp", "tp")})
    if cfg.qk_norm:
        # per-head norm weights are [L, hd] — every tp shard applies the
        # same head-local norm, so they replicate over tp
        layers.update({"q_norm": P("pp", None), "k_norm": P("pp", None)})
    if cfg.post_norms:
        layers.update(
            {"post_attn_norm": P("pp", None), "post_ffn_norm": P("pp", None)}
        )
    if cfg.n_experts:
        # Experts on ep, expert FFN hidden on tp: the dispatch einsums in
        # models/moe.py become the token all-to-all over ep under GSPMD.
        layers.update(
            {
                "router": P("pp", None, None),
                "w1e": P("pp", "ep", None, "tp"),
                "w3e": P("pp", "ep", None, "tp"),
                "w2e": P("pp", "ep", "tp", None),
            }
        )
        if cfg.n_shared_experts:
            layers.update(
                {
                    "w1s": P("pp", None, "tp"),
                    "w3s": P("pp", None, "tp"),
                    "w2s": P("pp", "tp", None),
                }
            )
    else:
        layers.update(
            {
                "w1": P("pp", None, "tp"),
                "w3": P("pp", None, "tp"),
                "w2": P("pp", "tp", None),
            }
        )
    specs: dict[str, Any] = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


# Spec per encoder leaf name (full table, unconditional). Biases shard with
# their projection's output axis; norms and position/type tables replicate.
_ENCODER_LAYER_SPECS: dict[str, Any] = {
    "attn_norm": P(None, None),
    "attn_norm_b": P(None, None),
    "wq": P(None, None, "tp"),
    "bq": P(None, "tp"),
    "wk": P(None, None, "tp"),
    "bk": P(None, "tp"),
    "wv": P(None, None, "tp"),
    "bv": P(None, "tp"),
    "wo": P(None, "tp", None),
    "bo": P(None, None),
    "ffn_norm": P(None, None),
    "ffn_norm_b": P(None, None),
    "w1": P(None, None, "tp"),
    "b1": P(None, "tp"),
    "w3": P(None, None, "tp"),
    "b3": P(None, "tp"),
    "w2": P(None, "tp", None),
    "b2": P(None, None),
}
_ENCODER_TOP_SPECS: dict[str, Any] = {
    "embed": P("tp", None),
    "pos_embed": P(None, None),
    "type_embed": P(None, None),
    "embed_norm": P(None),
    "embed_norm_b": P(None),
    "final_norm": P(None),
}


def embedder_param_specs(cfg: ModelConfig) -> dict[str, Any]:
    """Specs for models/embedder.py:init_embedder_params, derived from the
    init tree's OWN structure via eval_shape — the conditional leaf set
    (gated w3, norm/linear biases, pos/type tables, embed vs final norm)
    lives in exactly one place, so specs can never drift from params
    (place_params zips flattened specs against flattened params and a
    mismatch would silently shard the wrong leaves)."""
    import jax

    from ..models.embedder import init_embedder_params

    shapes = jax.eval_shape(
        lambda: init_embedder_params(cfg, jax.random.PRNGKey(0))
    )
    specs: dict[str, Any] = {}
    for key, sub in shapes.items():
        if key == "layers":
            specs["layers"] = {k: _ENCODER_LAYER_SPECS[k] for k in sub}
        else:
            specs[key] = _ENCODER_TOP_SPECS[key]
    return specs


def kv_cache_specs(quantized: bool = False, latent: bool = False) -> dict[str, Any]:
    # [L, B, Hkv, S, hd] — layers on pp, batch slots on dp, KV heads on tp.
    # The int8 cache ({"q", "s"} pytrees) shards the payload identically;
    # scales [L,B,Hkv,S] drop the trailing head_dim axis.
    if latent:
        # MLA latent cache [L, B, 1, S, R]: the fake one-head axis cannot
        # shard — every tp shard's heads read the SAME latent row, so it
        # replicates over tp and shards batch on dp only (models/mla.py).
        row = P("pp", "dp", None, None, None)
        if quantized:
            entry = {"q": row, "s": P("pp", "dp", None, None)}
            return {"k": entry, "v": entry}
        return {"k": row, "v": row}
    row = P("pp", "dp", "tp", None, None)
    if quantized:
        # Fused GQA layout: one payload block [L, B, 2*Hkv + p, S, hd] holding
        # K rows, V rows, and (when p == 1) a bit-packed scale pseudo-head.
        # The head axis is no longer a clean Hkv multiple, so it replicates
        # over tp and shards batch on dp only (int8 + mesh decodes via the
        # XLA path, which reads whole heads anyway).
        return {
            "k": {
                "q": P("pp", "dp", None, None, None),
                "s": P("pp", "dp", None, None),
            },
            "v": {},
        }
    return {"k": row, "v": row}


def kv_pool_specs(quantized: bool = False, latent: bool = False) -> dict[str, Any]:
    """Specs for the physical prefix pool (executor/physical.py pool_like):
    pool leaves are the arena leaves with batch→pool-row and S→block_tokens
    `[L, PXB, Hx, bt, ...]`. Axis-for-axis the cache specs apply, EXCEPT the
    pool-row axis replicates instead of sharding on dp — pool rows hold
    shared prefix blocks any slot on any dp shard may gather through its
    block table, so they are a global resource, not slot-partitioned."""
    def drop_dp(spec: Any) -> Any:
        if not isinstance(spec, P):
            return spec
        return P(*(None if ax == "dp" else ax for ax in spec))

    return jax.tree.map(
        drop_dp, kv_cache_specs(quantized=quantized, latent=latent),
        is_leaf=lambda x: isinstance(x, P),
    )


def supports_ragged_prefill(mesh: Mesh | None) -> bool:
    """Whether the ragged packed-prefill path (kernels/attention.py
    ragged_* family) may run under `mesh`.

    The ragged kernels take the packed [T] token buffer and the per-row
    (slot, start, len) descriptors as whole-array operands and stream cache
    blocks by absolute physical index. Rows bound for different dp shards
    interleave inside one packed buffer, and sp would split the per-row DMA
    descriptors mid-stream — any mesh with dp/sp/ep > 1 keeps the bucketed
    chunk path, which shards per kv_cache_specs. Pure pp×tp meshes are fine:
    the packed buffer replicates, heads/layers shard cleanly, and the engine
    forces the XLA ragged impl (no Pallas DMA descriptors) whenever
    mesh.size > 1."""
    if mesh is None or mesh.size == 1:
        return True
    shape = dict(mesh.shape)
    return all(shape.get(ax, 1) == 1 for ax in ("dp", "sp", "ep"))


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Place a pytree on the mesh according to matching PartitionSpecs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )
