"""Pipeline parallelism: GPipe-style microbatched prefill over the `pp` axis.

Absent from the reference (its only "pipeline" is the job queue). Here layer
stages are a real mesh dimension: the stacked layer tree `[L, ...]` is
reshaped to `[PP, L/PP, ...]` and sharded on `pp`, so each device holds only
its stage's weights in HBM — the memory-capacity escape hatch for models too
big for tensor parallelism alone (pp composes with tp for the biggest
configs).

Schedule: classic GPipe fill-drain. M microbatches flow through PP stages in
M + PP - 1 steps; stage p processes microbatch `i - p` at step i and hands
its activation to stage p+1 via `ppermute` (one ICI hop — stages are laid
out contiguously on the mesh). Everything is static-shaped: invalid
(bubble) steps compute on garbage and are masked out at the write, the
jit-friendly alternative to data-dependent control flow.

The per-layer math is `models.llama.prefill_layer` — the same function the
single-stage scan uses, so pipeline equivalence is testable to the bit.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.configs import ModelConfig
from ..models.llama import _embed_in, _logits, layer_windows, prefill_layer, prefill_masks
from .ring import _shard_map


def stack_stages(layers: Any, pp: int) -> Any:
    """[L, ...] stacked layer tree → [PP, L/PP, ...] stage-major tree."""
    def split(x):
        L = x.shape[0]
        assert L % pp == 0, f"n_layers {L} not divisible by pp={pp}"
        return x.reshape(pp, L // pp, *x.shape[1:])

    return jax.tree.map(split, layers)


def pipeline_prefill(
    cfg: ModelConfig,
    params: Any,
    tokens: jnp.ndarray,  # [B, S] int32
    lengths: jnp.ndarray,  # [B] int32
    mesh: Mesh,
    n_microbatches: int = 0,
    attn_impl: str = "xla",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Prefill with layers pipelined over the mesh's `pp` axis.

    Same contract as `llama_prefill`: (last_logits [B, V] f32,
    k [L, B, Hkv, S, hd], v [...]). B must divide into M microbatches.
    """
    PP = mesh.shape["pp"]
    B, S = tokens.shape
    M = n_microbatches or PP
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    mb = B // M
    L = cfg.n_layers
    Lp = L // PP
    Hkv, hd = cfg.n_kv_heads, cfg.resolved_head_dim

    h = _embed_in(cfg, params, tokens)  # [B, S, D] (embed replicated over pp)
    D = h.shape[-1]
    cos, sin, mask = prefill_masks(cfg, S, lengths)

    hm = h.reshape(M, mb, S, D)
    maskm = mask.reshape(M, mb, S, S)
    lenm = lengths.reshape(M, mb)

    stage_lp = stack_stages(params["layers"], PP)  # [PP, Lp, ...]
    stage_win = layer_windows(cfg).reshape(PP, Lp)  # per-stage sliding windows

    def run(stage_lp, stage_win, hm, maskm, lenm, cos, sin):
        # Local views: stage_lp leaves arrive as [1, Lp, ...].
        lp = jax.tree.map(lambda x: x[0], stage_lp)
        win = stage_win[0]  # [Lp]
        stage = jax.lax.axis_index("pp")
        steps = M + PP - 1

        def run_stage(x, mask_j, len_j):
            def layer(h, xs):
                one_lp, w = xs
                return prefill_layer(
                    cfg, one_lp, h, cos, sin, mask_j, len_j, attn_impl, window=w
                )

            return jax.lax.scan(layer, x, (lp, win))

        out0 = jnp.zeros((M, mb, S, D), dtype=h.dtype)
        kv0 = jnp.zeros((M, Lp, mb, Hkv, S, hd), dtype=h.dtype)
        x0 = jnp.zeros((mb, S, D), dtype=h.dtype)
        fwd = [(p, (p + 1) % PP) for p in range(PP)]

        def body(i, carry):
            x_in, outbuf, kbuf, vbuf = carry
            j = i - stage  # microbatch this stage handles at step i
            cj = jnp.clip(j, 0, M - 1)
            valid = (j >= 0) & (j < M)

            x = jnp.where(stage == 0, hm[jnp.clip(i, 0, M - 1)], x_in)
            y, (ks, vs) = run_stage(x, maskm[cj], lenm[cj])

            # Masked writes keep bubble steps from clobbering real results.
            kbuf = kbuf.at[cj].set(jnp.where(valid, ks, kbuf[cj]))
            vbuf = vbuf.at[cj].set(jnp.where(valid, vs, vbuf[cj]))
            w_out = valid & (stage == PP - 1)
            outbuf = outbuf.at[cj].set(jnp.where(w_out, y, outbuf[cj]))

            x_next = jax.lax.ppermute(y, "pp", fwd)
            return x_next, outbuf, kbuf, vbuf

        _, outbuf, kbuf, vbuf = jax.lax.fori_loop(
            0, steps, body, (x0, out0, kv0, kv0)
        )
        # Only the last stage holds real outputs; make them replicated.
        outbuf = jnp.where(stage == PP - 1, outbuf, 0.0)
        outbuf = jax.lax.psum(outbuf, "pp")
        return outbuf, kbuf, vbuf

    shmap = _shard_map(
        run,
        mesh,
        in_specs=(P("pp"), P("pp"), P(), P(), P(), P(), P()),
        out_specs=(P(), P(None, "pp"), P(None, "pp")),
    )
    out, k, v = shmap(stage_lp, stage_win, hm, maskm, lenm, cos, sin)

    h = out.reshape(B, S, D)
    # [M, L, mb, Hkv, S, hd] → [L, B, Hkv, S, hd]
    k = jnp.moveaxis(k, 0, 1).reshape(L, B, Hkv, S, hd)
    v = jnp.moveaxis(v, 0, 1).reshape(L, B, Hkv, S, hd)

    last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    return _logits(cfg, params, last), k, v
