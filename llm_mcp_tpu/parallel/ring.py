"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference's long-context story is routing policy only — prompts are
bucketed by estimated length and sent to bigger model tiers or the cloud
(`core/internal/routing/router.go:92-123,420-447`); no computation is ever
split across devices. Here long context is a real subsystem: when a prompt
exceeds one chip's HBM (KV + activations), prefill shards the *sequence*
axis over the mesh's `sp` axis and the attention collectives ride ICI.

Two interchangeable context-parallel schemes, both SPMD under `shard_map`:

  - **Ring attention** (`ring_attention_local`): K/V shards rotate around
    the `sp` ring via `lax.ppermute` while each device's Q shard accumulates
    online-softmax partials (flash-attention style m/l/acc carry). Compute
    for chunks entirely in the causal future is skipped with `lax.cond`, so
    the causal ring does ~half the FLOPs of the naive rotation. Peak memory
    per chip is O(S/sp · hd) for K/V — sequence length scales linearly with
    the number of chips.
  - **Ulysses all-to-all** (`ulysses_attention_local`): two `all_to_all`s
    trade the sequence sharding for a head sharding, run ordinary dense
    causal attention on full-length sequences with H/sp local heads, and
    trade back. Cheaper collectives on small meshes; requires
    sp | n_kv_heads.

`llama_prefill_sp` runs the whole Llama prefill under one `shard_map` with
Megatron-style tensor parallelism (vocab-parallel embedding + logits, psum
after wo/w2) composed with either context-parallel attention — tokens arrive
sharded [dp, sp], weights sharded on tp, and the returned KV shards land
directly in the engine cache's [.., tp, sp, ..] layout without any gather.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.configs import ModelConfig
from ..ops.rope import rope_tables, apply_rope

NEG_INF = float(-1e30)


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map with the replication check off (ppermute/cond carries
    confuse varying-manual-axes inference; correctness is asserted by tests
    against the single-device reference)."""
    smap = getattr(jax, "shard_map", None)
    if smap is None:  # pre-0.5 jax: only the experimental spelling exists
        from jax.experimental.shard_map import shard_map as smap
    try:
        return smap(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:  # older spelling of the replication-check kwarg
        return smap(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


# ---------------------------------------------------------------------------
# Ring attention (causal, GQA, length-masked)
# ---------------------------------------------------------------------------


def ring_attention_local(
    q: jnp.ndarray,  # [B, H, Sl, hd] — local query shard (S sharded on axis)
    k: jnp.ndarray,  # [B, Hkv, Sl, hd]
    v: jnp.ndarray,  # [B, Hkv, Sl, hd]
    lengths: jnp.ndarray,  # [B] int32 global valid lengths (replicated)
    *,
    axis_name: str = "sp",
    window: jnp.ndarray | int = 0,  # sliding window (0 = global); may be traced
    softcap: float = 0.0,  # Gemma2-style score capping (0 = off)
    scale: float = 0.0,  # query scale override (0 = head_dim**-0.5)
) -> jnp.ndarray:
    """Causal GQA attention with K/V rotating around the `axis_name` ring.

    Call inside `shard_map` with the sequence axis sharded over `axis_name`.
    Online softmax makes the P-step accumulation exact (not approximate);
    tests assert bitwise-tolerance agreement with dense attention. Sliding
    windows and score softcaps thread through so the windowed families
    (Mistral/Gemma2) long-context-prefill like plain Llama.
    """
    B, H, Sl, hd = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    nshards = jax.lax.psum(1, axis_name)  # static: axis size
    idx = jax.lax.axis_index(axis_name)
    window = jnp.asarray(window, dtype=jnp.int32)

    qg = (q.astype(jnp.float32) * (scale or hd**-0.5)).reshape(B, Hkv, G, Sl, hd)
    q_pos = idx * Sl + jnp.arange(Sl, dtype=jnp.int32)  # [Sl] global positions

    acc = jnp.zeros((B, Hkv, G, Sl, hd), jnp.float32)
    m = jnp.full((B, Hkv, G, Sl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hkv, G, Sl, 1), jnp.float32)
    perm = [(j, (j + 1) % nshards) for j in range(nshards)]

    def step(t, carry):
        acc, m, l, k, v = carry
        src = jnp.mod(idx - t, nshards)  # origin shard of the current chunk
        k_pos = src * Sl + jnp.arange(Sl, dtype=jnp.int32)  # [Sl]
        kf = k.astype(jnp.float32)
        vf = v.astype(jnp.float32)

        def compute(acc, m, l):
            s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kf)
            if softcap:
                s = jnp.tanh(s / softcap) * softcap
            causal = k_pos[None, :] <= q_pos[:, None]  # [Slq, Slk]
            causal &= (window == 0) | (q_pos[:, None] - k_pos[None, :] < window)
            valid = k_pos[None, :] < lengths[:, None]  # [B, Slk]
            mask = causal[None, None, None] & valid[:, None, None, None, :]
            s = jnp.where(mask, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            # Mask p explicitly: for a fully-masked row m_new stays NEG_INF
            # and exp(s - m_new) would be 1, silently averaging V.
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum("bhgqk,bhkd->bhgqd", p, vf)
            return acc_new, m_new, l_new

        # Chunks entirely in the causal future contribute nothing — skip the
        # matmuls (the ring still rotates so later steps see the data).
        acc, m, l = jax.lax.cond(
            src <= idx, compute, lambda a, mm, ll: (a, mm, ll), acc, m, l
        )

        def rotate(kv):
            k, v = kv
            return (
                jax.lax.ppermute(k, axis_name, perm),
                jax.lax.ppermute(v, axis_name, perm),
            )

        # The last rotation's result is discarded — skip the ICI transfer.
        k, v = jax.lax.cond(t < nshards - 1, rotate, lambda kv: kv, (k, v))
        return acc, m, l, k, v

    acc, m, l, _, _ = jax.lax.fori_loop(0, nshards, step, (acc, m, l, k, v))
    # Rows that saw no valid key (padding beyond `lengths`) emit 0, not NaN.
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    return out.reshape(B, H, Sl, hd).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) context parallelism
# ---------------------------------------------------------------------------


def _dense_causal_attention(
    qg, k, v, lengths, pos_offset=0, window=0, softcap=0.0, scale=0.0
):
    """Reference dense causal GQA attention.  qg [B, Hkv, G, S, hd]."""
    B, Hkv, G, S, hd = qg.shape
    window = jnp.asarray(window, dtype=jnp.int32)
    s = jnp.einsum(
        "bhgqd,bhkd->bhgqk",
        qg.astype(jnp.float32) * (scale or hd**-0.5),
        k.astype(jnp.float32),
    )
    if softcap:
        s = jnp.tanh(s / softcap) * softcap
    pos = pos_offset + jnp.arange(S, dtype=jnp.int32)
    causal = pos[None, :] <= pos[:, None]
    causal &= (window == 0) | (pos[:, None] - pos[None, :] < window)
    valid = pos[None, :] < lengths[:, None]
    mask = causal[None, None, None] & valid[:, None, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(mask, jnp.exp(s - m), 0.0)  # fully-masked rows → l == 0
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32))
    return jnp.where(l > 0, out / jnp.where(l > 0, l, 1.0), 0.0)


def ulysses_attention_local(
    q: jnp.ndarray,  # [B, H, Sl, hd]
    k: jnp.ndarray,  # [B, Hkv, Sl, hd]
    v: jnp.ndarray,  # [B, Hkv, Sl, hd]
    lengths: jnp.ndarray,  # [B] int32
    *,
    axis_name: str = "sp",
    window: jnp.ndarray | int = 0,
    softcap: float = 0.0,
    scale: float = 0.0,
) -> jnp.ndarray:
    """All-to-all context parallelism (Ulysses): swap S-sharding for
    head-sharding, attend dense over the full sequence, swap back.

    Requires axis size | n_kv_heads (each shard keeps whole GQA groups).
    """
    B, H, Sl, hd = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    nshards = jax.lax.psum(1, axis_name)
    if Hkv % nshards:
        raise ValueError(
            f"ulysses needs axis size {nshards} | kv heads {Hkv}; use ring instead"
        )
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # [B, H, Sl, hd] -> [B, H/P, S, hd]: contiguous head blocks keep GQA
    # groups aligned with their KV heads as long as P | Hkv.
    qh = a2a(q, split_axis=1, concat_axis=2)
    kh = a2a(k, split_axis=1, concat_axis=2)
    vh = a2a(v, split_axis=1, concat_axis=2)
    Hl = qh.shape[1]
    out = _dense_causal_attention(
        qh.reshape(B, Hl // G, G, qh.shape[2], hd), kh, vh, lengths,
        window=window, softcap=softcap, scale=scale,
    )
    out = out.reshape(B, Hl, -1, hd).astype(q.dtype)
    return a2a(out, split_axis=2, concat_axis=1)  # back to [B, H, Sl, hd]


# ---------------------------------------------------------------------------
# Standalone sharded attention entrypoints
# ---------------------------------------------------------------------------

_ATTN_IMPLS = {"ring": ring_attention_local, "ulysses": ulysses_attention_local}


def sp_prefill_attention(
    mesh: Mesh,
    q: jnp.ndarray,  # [B, H, S, hd] global
    k: jnp.ndarray,  # [B, Hkv, S, hd]
    v: jnp.ndarray,  # [B, Hkv, S, hd]
    lengths: jnp.ndarray,  # [B]
    impl: str = "ring",
) -> jnp.ndarray:
    """Context-parallel causal attention over the full mesh: batch on dp,
    heads on tp, sequence on sp."""
    fn = functools.partial(_ATTN_IMPLS[impl], axis_name="sp")
    spec_q = P("dp", "tp", "sp", None)
    spec_kv = P("dp", "tp", "sp", None)
    return _shard_map(
        fn, mesh, (spec_q, spec_kv, spec_kv, P("dp")), spec_q
    )(q, k, v, lengths)


# ---------------------------------------------------------------------------
# Full sequence-parallel Llama prefill (SP × TP × DP under one shard_map)
# ---------------------------------------------------------------------------


def llama_prefill_sp(
    cfg: ModelConfig,
    params: dict[str, Any],
    tokens: jnp.ndarray,  # [B, S] int32, S sharded over sp
    lengths: jnp.ndarray,  # [B] int32 true prompt lengths
    mesh: Mesh,
    attn_impl: str = "ring",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Long-context prefill with the sequence axis sharded over `sp` and
    Megatron tensor parallelism over `tp`, all inside one shard_map.

    Equivalent to `models.llama.llama_prefill` (tests assert agreement) but
    activations are [B, S/sp, D] per chip and K/V shards are produced
    directly in the engine cache's sharded layout — no full-sequence gather
    ever materializes. This is what lets one serving process accept prompts
    whose KV exceeds a single chip's HBM.

    Composes with the whole family surface (Qwen biases, Gemma offset norms
    / softcaps / embed scale / post-norms, Mistral/Gemma2 sliding windows via
    per-layer window masks threaded into the ring/Ulysses kernels) and with
    int8-quantized weights (the shared `qdot`/`embed_lookup`/`logits_head`
    ops dequantize inside the shard_map). MoE stays on the GSPMD prefill
    path — its expert all-to-all belongs to the `ep` axis, not `sp`.
    """
    from ..models.llama import (  # local import to avoid cycle
        _act,
        _norm,
        _qkv,
        _softcap,
        layer_windows,
    )
    from ..models.quant import embed_lookup, is_quantized, logits_head, qdot
    from .sharding import llama_param_specs  # local import to avoid cycle

    if cfg.n_experts:
        raise ValueError("sp prefill does not cover MoE (experts ride ep)")
    hd = cfg.resolved_head_dim
    H, Hkv = cfg.n_heads, cfg.n_kv_heads
    tp = mesh.shape["tp"]
    sp = mesh.shape["sp"]
    if Hkv % tp or cfg.vocab_size % tp:
        raise ValueError(f"tp={tp} must divide n_kv_heads={Hkv} and vocab")
    if tokens.shape[1] % sp:
        raise ValueError(f"sp={sp} must divide sequence {tokens.shape[1]}")
    if attn_impl == "ulysses" and (Hkv // tp) % sp:
        raise ValueError(
            f"ulysses needs sp={sp} | local kv heads {Hkv // tp}; use ring"
        )
    attn = functools.partial(
        _ATTN_IMPLS[attn_impl],
        axis_name="sp",
        softcap=cfg.attn_softcap,
        scale=cfg.attn_scale,
    )

    def local_fn(params, tokens, lengths):
        Bl, Sl = tokens.shape
        Hl, Hkvl = H // tp, Hkv // tp
        sp_idx = jax.lax.axis_index("sp")
        tp_idx = jax.lax.axis_index("tp")
        s0 = sp_idx * Sl  # global position offset of this sequence shard

        # Vocab-parallel embedding: each tp shard holds [V/tp, D]; lookups
        # outside the local range contribute 0 and psum restores the row
        # (embed_lookup dequantizes int8 embedding rows in place).
        embed = params["embed"]
        Vl = embed["q"].shape[0] if isinstance(embed, dict) else embed.shape[0]
        v0 = tp_idx * Vl
        local_ids = tokens - v0
        in_range = (local_ids >= 0) & (local_ids < Vl)
        rows = embed_lookup(embed, jnp.clip(local_ids, 0, Vl - 1))
        h = rows * in_range[..., None].astype(rows.dtype)
        h = jax.lax.psum(h, "tp")  # [Bl, Sl, D]
        if cfg.embed_scale:
            h = h * jnp.asarray(cfg.dim**0.5, dtype=h.dtype)

        positions = (s0 + jnp.arange(Sl, dtype=jnp.int32))[None, :]
        cos, sin = rope_tables(cfg, hd, positions)

        def layer(h, xs):
            lp, win = xs
            x = _norm(cfg, h, lp["attn_norm"])
            q, k, v = _qkv(cfg, lp, x)  # qdot: dequant + bias, tp-local
            q = apply_rope(q.reshape(Bl, Sl, Hl, hd), cos, sin)
            k = apply_rope(k.reshape(Bl, Sl, Hkvl, hd), cos, sin)
            v = v.reshape(Bl, Sl, Hkvl, hd)
            kh = k.transpose(0, 2, 1, 3)  # [Bl, Hkvl, Sl, hd]
            vh = v.transpose(0, 2, 1, 3)
            ctx = attn(q.transpose(0, 2, 1, 3), kh, vh, lengths, window=win)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(Bl, Sl, Hl * hd)
            # wo input dim sharded on tp — partial products reduce over tp
            # BEFORE any post-norm (norming partial sums would be wrong math).
            out = jax.lax.psum(qdot(ctx, lp["wo"]), "tp")
            if cfg.post_norms:
                out = _norm(cfg, out, lp["post_attn_norm"])
            h = h + out

            x = _norm(cfg, h, lp["ffn_norm"])
            gate = _act(cfg, qdot(x, lp["w1"]))
            up = qdot(x, lp["w3"])
            out = jax.lax.psum(qdot(gate * up, lp["w2"]), "tp")
            if cfg.post_norms:
                out = _norm(cfg, out, lp["post_ffn_norm"])
            h = h + out
            return h, (kh, vh)

        h, (ks, vs) = jax.lax.scan(layer, h, (params["layers"], layer_windows(cfg)))

        # The last valid position lives on exactly one sp shard: every shard
        # contributes its row (or zeros) and a psum over sp assembles [Bl, D].
        last_pos = lengths - 1  # [Bl] global
        local_last = jnp.clip(last_pos - s0, 0, Sl - 1)
        mine = (last_pos >= s0) & (last_pos < s0 + Sl)
        h_last = jnp.take_along_axis(h, local_last[:, None, None], axis=1)[:, 0]
        h_last = jax.lax.psum(h_last * mine[:, None].astype(h_last.dtype), "sp")

        h_last = _norm(cfg, h_last, params["final_norm"])
        src = params["embed"] if cfg.tie_embeddings else params["lm_head"]
        # vocab-parallel logits [B, V/tp] (logits_head dequantizes int8 heads)
        logits = _softcap(
            logits_head(src, h_last, tied=cfg.tie_embeddings), cfg.logit_softcap
        )
        return logits, ks, vs

    pspecs = llama_param_specs(cfg)
    if is_quantized(params["layers"]["wq"]):
        from ..models.quant import quantized_specs

        pspecs = quantized_specs(pspecs)
    out_specs = (
        P("dp", "tp"),  # vocab-parallel logits [B, V]
        P(None, "dp", "tp", "sp", None),  # ks [L, B, Hkv, S, hd]
        P(None, "dp", "tp", "sp", None),  # vs
    )
    return _shard_map(
        local_fn, mesh, (pspecs, P("dp", "sp"), P("dp")), out_specs
    )(params, tokens, lengths)
