from .mesh import make_mesh, mesh_axis_sizes
from .sharding import llama_param_specs, kv_cache_specs, embedder_param_specs, shard_pytree

__all__ = [
    "make_mesh",
    "mesh_axis_sizes",
    "llama_param_specs",
    "kv_cache_specs",
    "embedder_param_specs",
    "shard_pytree",
]
