from .mesh import make_mesh, mesh_axis_sizes
from .sharding import llama_param_specs, kv_cache_specs, embedder_param_specs, shard_pytree
from .ring import (
    ring_attention_local,
    ulysses_attention_local,
    sp_prefill_attention,
    llama_prefill_sp,
)
from .pipeline import pipeline_prefill, stack_stages

__all__ = [
    "pipeline_prefill",
    "stack_stages",
    "make_mesh",
    "mesh_axis_sizes",
    "llama_param_specs",
    "kv_cache_specs",
    "embedder_param_specs",
    "shard_pytree",
    "ring_attention_local",
    "ulysses_attention_local",
    "sp_prefill_attention",
    "llama_prefill_sp",
]
