"""Device mesh construction.

The reference's "parallelism" is cluster-level (N workers × devices ×
concurrency caps, SURVEY.md §2.2); intra-model parallelism did not exist.
Here it does: a `jax.sharding.Mesh` with axes

  dp — data parallel (independent batch slots)
  pp — pipeline parallel (layer stages, GPipe microbatching — parallel/pipeline.py)
  ep — expert parallel (MoE expert shards — models/moe.py)
  sp — sequence parallel (long-context prefill; ring attention — parallel/ring.py)
  tp — tensor parallel (attention heads / FFN hidden sharded over ICI)

`tp` is the innermost (fastest-varying) axis so its collectives ride the
shortest ICI hops; `sp` sits next for the ring permutes. XLA inserts the
collectives (all-gather / reduce-scatter / psum / all-to-all) implied by the
shardings. Multi-host extends the same mesh over DCN via
`jax.distributed.initialize` (see parallel/distributed.py).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXES = ("dp", "pp", "ep", "sp", "tp")


def mesh_axis_sizes(spec: str, n_devices: int) -> dict[str, int]:
    """Parse "dp=2,tp=4" → {'dp': 2, 'pp': 1, 'ep': 1, 'sp': 1, 'tp': 4};
    default all-TP.

    TP is the default because decode is HBM-bandwidth-bound: sharding the
    weights over all chips divides bytes-per-step per chip, which is what
    lifts tokens/sec/chip (scaling-book recipe).
    """
    sizes = {a: 1 for a in AXES}
    spec = (spec or "").strip()
    if spec:
        for part in spec.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k in sizes and v.strip():
                sizes[k] = int(v)
        got = 1
        for a in AXES:
            got *= sizes[a]
        if got != n_devices:
            raise ValueError(f"mesh spec {spec!r} = {got} devices, have {n_devices}")
    else:
        sizes["tp"] = n_devices
    return sizes


def make_mesh(spec: str = "", devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    sizes = mesh_axis_sizes(spec, len(devices))
    arr = np.asarray(devices).reshape(*(sizes[a] for a in AXES))
    return Mesh(arr, axis_names=AXES)
