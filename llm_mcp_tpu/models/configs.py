"""Model architecture configs for the TPU executor.

The reference delegates all model execution to Ollama's catalog (models are
just names + inferred metadata, `discovery.go:482-560`). Here models are real
in-process architectures. Flagship targets per BASELINE.json configs:
Llama-3.1-8B (decoder, chat), nomic-embed-text and qwen3-embedding-8b
(encoders, embeddings with Matryoshka truncation).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ModelConfig:
    name: str
    arch: str = "llama"  # llama (causal decoder) | encoder (bidirectional embedder)
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14_336
    head_dim: int = 0  # 0 → dim // n_heads
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 131_072
    # MoE fields (0 experts → dense FFN)
    n_experts: int = 0
    experts_per_tok: int = 2
    capacity_factor: float = 1.25
    # encoder-only fields
    pooling: str = "mean"  # mean | cls
    embed_dim: int = 0  # output embedding dim (0 → dim)
    # encoder (BERT-family) variation knobs — one shared bidirectional
    # encoder serves nomic/BERT checkpoints the way one decoder serves the
    # llama families (models/embedder.py honors all of these):
    enc_norm: str = "rms"  # rms | layer (LayerNorm with learned bias)
    enc_post_ln: bool = False  # BERT/nomic: post-LN residuals + embedding LN
    enc_pos: str = "rope"  # rope | learned (absolute position table)
    enc_gated: bool = True  # gated MLP (SwiGLU); False = fc1→act→fc2 (BERT)
    enc_bias: bool = False  # biases on attention/MLP linears (classic BERT)
    type_vocab_size: int = 0  # BERT segment embeddings (segment 0 at inference)
    # family variation knobs (one shared decoder serves all families, the
    # way the reference's one Ollama runtime serves its whole catalog):
    qkv_bias: bool = False  # Qwen2: biases on q/k/v projections
    qk_norm: bool = False  # Qwen3: per-head RMSNorm on q/k before rope
    act: str = "silu"  # FFN activation: silu (llama/qwen/mistral) | gelu (gemma)
    norm_weight_offset: float = 0.0  # Gemma: RMSNorm computes x * (1 + w)
    embed_scale: bool = False  # Gemma: hidden = embed * sqrt(dim)
    logit_softcap: float = 0.0  # Gemma2: logits = cap * tanh(logits / cap)
    attn_softcap: float = 0.0  # Gemma2: same cap on attention scores
    sliding_window: int = 0  # Mistral/Gemma2: local-attention window (0 = off)
    # Gemma2 query_pre_attn_scalar: scores scale by this**-0.5 instead of
    # head_dim**-0.5 (9B: dim/n_heads = 224 while head_dim = 256). 0 → head_dim.
    query_pre_attn_scalar: float = 0.0
    # every `sliding_pattern`-th layer is GLOBAL, the rest sliding
    # (1 = all layers sliding, Mistral; 2 = alternating, Gemma2)
    sliding_pattern: int = 1
    post_norms: bool = False  # Gemma2: extra RMSNorm after attn and after FFN
    # MLA (DeepSeek-V2/V3 multi-head latent attention, arch="mla"): q/kv
    # project through low-rank latents; the KV cache stores ONE latent
    # vector (+ a shared rope key) per token instead of per-head K/V —
    # kv_lora_rank + qk_rope_head_dim floats/token vs 2*n_kv_heads*head_dim
    # (e.g. 576 vs 2048 at 8B-class GQA: ~3.6x more context per HBM byte).
    q_lora_rank: int = 0  # 0 → dense q projection (V2-Lite style)
    kv_lora_rank: int = 0  # >0 enables MLA
    qk_rope_head_dim: int = 0  # per-head rope dims (shared key)
    qk_nope_head_dim: int = 0  # per-head non-rope dims
    v_head_dim: int = 0  # per-head value dims
    # rope scaling for long context: factor > 1 switches
    # `ops/rope.py:rope_tables` to the family's corrected frequencies —
    # rope_type "yarn" (DeepSeek-V2; yarn_mscale_all_dim also scales
    # attention scores via attn_scale/mla_scale) or "llama3" (Llama-3.x
    # wavelength-banded scaling)
    rope_type: str = "yarn"
    rope_factor: float = 1.0
    rope_orig_max: int = 0  # original_max_position_embeddings pre-scaling
    llama3_low_freq_factor: float = 1.0
    llama3_high_freq_factor: float = 4.0
    yarn_beta_fast: float = 32.0
    yarn_beta_slow: float = 1.0
    yarn_mscale: float = 0.0
    yarn_mscale_all_dim: float = 0.0
    # DeepSeek-MoE structure (beyond the Mixtral-style all-MoE fields above):
    # `n_shared_experts` dense always-on experts added to the routed output;
    # routed experts use `moe_ffn_hidden` (0 → ffn_hidden); the first
    # `first_dense_layers` decoder layers keep a dense FFN (V2-Lite: 1);
    # norm_topk_prob=False keeps raw softmax gates (scaled by
    # routed_scaling_factor) instead of renormalizing the top-k
    n_shared_experts: int = 0
    moe_ffn_hidden: int = 0
    first_dense_layers: int = 0
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    # serving metadata
    params_b: float = 0.0
    tie_embeddings: bool = False

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.dim // self.n_heads

    @property
    def yarn_attn_mscale(self) -> float:
        """Yarn's score-scale correction: (0.1·m·ln(factor)+1)² when
        mscale_all_dim is set (DeepSeek-V2), else 1."""
        if self.rope_factor > 1.0 and self.yarn_mscale_all_dim:
            import math

            m = 0.1 * self.yarn_mscale_all_dim * math.log(self.rope_factor) + 1.0
            return m * m
        return 1.0

    @property
    def attn_scale(self) -> float:
        return (
            self.query_pre_attn_scalar or self.resolved_head_dim
        ) ** -0.5 * self.yarn_attn_mscale

    def param_count(self) -> int:
        """Approximate parameter count (embedding + layers + head)."""
        hd = self.resolved_head_dim
        ffn = 3 * self.dim * self.ffn_hidden
        ffn_total = self.n_layers * ffn
        if self.n_experts:
            moe_f = self.moe_ffn_hidden or self.ffn_hidden
            routed = 3 * self.dim * moe_f * self.n_experts
            shared = 3 * self.dim * moe_f * self.n_shared_experts
            moe_layer = routed + shared + self.dim * self.n_experts  # + router
            k = self.first_dense_layers
            ffn_total = k * ffn + (self.n_layers - k) * moe_layer
        if self.kv_lora_rank:  # MLA factorized attention
            dn, dr, dv = self.qk_nope_head_dim, self.qk_rope_head_dim, self.v_head_dim
            attn = (
                self.dim * self.n_heads * (dn + dr)  # q proj (dense-q)
                + self.dim * (self.kv_lora_rank + dr)  # kv down + rope key
                + self.kv_lora_rank * self.n_heads * (dn + dv)  # kv up
                + self.n_heads * dv * self.dim  # o proj
            )
        else:
            attn = (
                self.dim * self.n_heads * hd  # wq
                + 2 * self.dim * self.n_kv_heads * hd  # wk, wv
                + self.n_heads * hd * self.dim  # wo
            )
        per_layer_rest = attn + 2 * self.dim  # + norms
        embed = self.vocab_size * self.dim
        head = 0 if self.tie_embeddings or self.arch == "encoder" else self.vocab_size * self.dim
        return embed + self.n_layers * per_layer_rest + ffn_total + head + self.dim


# Canonical architectures. Llama-3.1-8B per the published architecture
# (32 layers, 4096 dim, 32 heads / 8 KV heads GQA, 14336 FFN, 128k vocab,
# rope theta 5e5). The reference's catalog rows for these names carry only
# inferred metadata (tier/context_k, `04_smart_routing.sql:18-31`).
MODEL_CONFIGS: dict[str, ModelConfig] = {
    "llama-3.1-8b": ModelConfig(
        name="llama-3.1-8b",
        rope_type="llama3",
        rope_factor=8.0,
        rope_orig_max=8192,
        vocab_size=128_256,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=14_336,
        rope_theta=500_000.0,
        max_seq_len=131_072,
        params_b=8.0,
    ),
    "llama-3.2-1b": ModelConfig(
        name="llama-3.2-1b",
        rope_type="llama3",
        rope_factor=32.0,
        rope_orig_max=8192,
        vocab_size=128_256,
        dim=2048,
        n_layers=16,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=8192,
        rope_theta=500_000.0,
        max_seq_len=131_072,
        params_b=1.24,
        tie_embeddings=True,
    ),
    # MLA (DeepSeek-style latent attention) at llama-8B-scale proportions:
    # an in-repo long-context serving config (NOT a published checkpoint) —
    # its KV cache costs 576 values/token/layer vs llama-3.1-8b's 2048, so
    # the same HBM serves ~3.6x the (slots x context). models/mla.py.
    "mla-8b": ModelConfig(
        name="mla-8b",
        arch="mla",
        vocab_size=128_256,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=1,  # latent cache: one shared row per token
        ffn_hidden=14_336,
        rope_theta=500_000.0,
        max_seq_len=131_072,
        kv_lora_rank=512,
        qk_rope_head_dim=64,
        qk_nope_head_dim=128,
        v_head_dim=128,
        params_b=9.2,
    ),
    # DeepSeek-V2-Lite — a PUBLISHED MLA+MoE checkpoint (HF
    # deepseek-ai/DeepSeek-V2-Lite config.json): dense layer 0, 26 MoE
    # layers of 64 routed + 2 shared experts, yarn rope 4k→160k. Loads via
    # models/weights.py (kv_a_proj_with_mqa / kv_b_proj / mlp.experts.* /
    # mlp.shared_experts.* mapping incl. the rope-dim de-interleave).
    "deepseek-v2-lite": ModelConfig(
        name="deepseek-v2-lite",
        arch="mla",
        vocab_size=102_400,
        dim=2048,
        n_layers=27,
        n_heads=16,
        n_kv_heads=1,
        ffn_hidden=10_944,
        norm_eps=1e-6,
        rope_theta=10_000.0,
        max_seq_len=163_840,
        kv_lora_rank=512,
        qk_rope_head_dim=64,
        qk_nope_head_dim=128,
        v_head_dim=128,
        n_experts=64,
        experts_per_tok=6,
        n_shared_experts=2,
        moe_ffn_hidden=1408,
        first_dense_layers=1,
        norm_topk_prob=False,
        routed_scaling_factor=1.0,
        rope_factor=40.0,
        rope_orig_max=4096,
        yarn_beta_fast=32.0,
        yarn_beta_slow=1.0,
        yarn_mscale=0.707,
        yarn_mscale_all_dim=0.707,
        params_b=15.7,
    ),
    # tiny V2-structure config for tests: dense layer 0 + MoE layers with
    # shared experts + yarn rope — every DeepSeek-V2 mechanism at toy size.
    "tiny-v2": ModelConfig(
        name="tiny-v2",
        arch="mla",
        vocab_size=512,
        dim=128,
        n_layers=3,
        n_heads=4,
        n_kv_heads=1,
        ffn_hidden=256,
        norm_eps=1e-6,
        rope_theta=10_000.0,
        max_seq_len=512,
        kv_lora_rank=32,
        qk_rope_head_dim=16,
        qk_nope_head_dim=32,
        v_head_dim=32,
        n_experts=4,
        experts_per_tok=2,
        n_shared_experts=2,
        moe_ffn_hidden=64,
        first_dense_layers=1,
        norm_topk_prob=False,
        routed_scaling_factor=1.0,
        rope_factor=4.0,
        rope_orig_max=64,
        yarn_mscale=0.707,
        yarn_mscale_all_dim=0.707,
        tie_embeddings=True,
        params_b=0.002,
    ),
    "tiny-mla": ModelConfig(
        name="tiny-mla",
        arch="mla",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=1,
        ffn_hidden=256,
        rope_theta=10_000.0,
        max_seq_len=512,
        kv_lora_rank=32,
        qk_rope_head_dim=16,
        qk_nope_head_dim=32,
        v_head_dim=32,
        tie_embeddings=True,
        params_b=0.001,
    ),
    # Tiny config for tests / CPU dev — same code paths, toy sizes.
    "tiny-llm": ModelConfig(
        name="tiny-llm",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        rope_theta=10_000.0,
        max_seq_len=512,
        params_b=0.001,
        tie_embeddings=True,
    ),
    # Mixtral 8x7B per the published architecture (32 layers, 4096 dim,
    # 32/8 GQA heads, 14336 expert FFN, 8 experts top-2, 32k vocab).
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32_000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=14_336,
        rope_theta=1_000_000.0,
        max_seq_len=32_768,
        n_experts=8,
        experts_per_tok=2,
        params_b=46.7,
    ),
    # Tiny MoE config for tests / CPU dev — same code paths, toy sizes.
    "tiny-moe": ModelConfig(
        name="tiny-moe",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        rope_theta=10_000.0,
        max_seq_len=512,
        n_experts=4,
        experts_per_tok=2,
        # E/k = 2.0 ⇒ capacity = T: dropless even at prefill, so tests can
        # assert decode == prefill == pipelined prefill bit-for-bit.
        capacity_factor=2.0,
        params_b=0.002,
        tie_embeddings=True,
    ),
    # Qwen2.5 per the published architecture: GQA with q/k/v biases,
    # untied head at 7B (tied at 0.5B), 1M rope theta, 152k vocab.
    "qwen2.5-7b": ModelConfig(
        name="qwen2.5-7b",
        vocab_size=152_064,
        dim=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        ffn_hidden=18_944,
        rope_theta=1_000_000.0,
        norm_eps=1e-6,
        max_seq_len=32_768,
        qkv_bias=True,
        params_b=7.6,
    ),
    # Qwen3 per the published architecture (Qwen/Qwen3-8B config.json):
    # biases gone, per-head q/k RMSNorm before rope, explicit head_dim,
    # untied head at 8B.
    "qwen3-8b": ModelConfig(
        name="qwen3-8b",
        vocab_size=151_936,
        dim=4096,
        n_layers=36,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=12_288,
        head_dim=128,
        rope_theta=1_000_000.0,
        norm_eps=1e-6,
        max_seq_len=32_768,
        qk_norm=True,
        params_b=8.2,
    ),
    # DeepSeek-R1 distills — the local deepseek models the reference's
    # smart routing seeds and tier-infers (`db/migrations/04_smart_routing
    # .sql:20,35`, `discovery.go:510` thinking-model detection). They are
    # published Qwen2.5/Llama-3.x checkpoints fine-tuned for <think>
    # reasoning, so the existing families serve them verbatim (think-tag
    # splitting: utils/tokens.py:split_think).
    "deepseek-r1-distill-qwen-1.5b": ModelConfig(
        name="deepseek-r1-distill-qwen-1.5b",
        vocab_size=151_936,
        dim=1536,
        n_layers=28,
        n_heads=12,
        n_kv_heads=2,
        ffn_hidden=8960,
        rope_theta=10_000.0,
        norm_eps=1e-6,
        max_seq_len=131_072,
        qkv_bias=True,  # Qwen2 architecture keeps attention biases
        tie_embeddings=True,
        params_b=1.78,
    ),
    "deepseek-r1-distill-llama-8b": ModelConfig(
        name="deepseek-r1-distill-llama-8b",
        vocab_size=128_256,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=14_336,
        rope_theta=500_000.0,
        max_seq_len=131_072,
        params_b=8.0,
    ),
    "qwen2.5-0.5b": ModelConfig(
        name="qwen2.5-0.5b",
        vocab_size=151_936,
        dim=896,
        n_layers=24,
        n_heads=14,
        n_kv_heads=2,
        ffn_hidden=4864,
        rope_theta=1_000_000.0,
        norm_eps=1e-6,
        max_seq_len=32_768,
        qkv_bias=True,
        tie_embeddings=True,
        params_b=0.49,
    ),
    # Mistral-7B-v0.1: llama-shaped GQA with a 4096-token sliding window on
    # every layer.
    "mistral-7b": ModelConfig(
        name="mistral-7b",
        vocab_size=32_000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=14_336,
        rope_theta=10_000.0,
        max_seq_len=32_768,
        sliding_window=4096,
        sliding_pattern=1,
        params_b=7.2,
    ),
    # Gemma-2-9B: gelu FFN, (1+w) RMSNorm with post-norms, sqrt(dim) embed
    # scaling, attention/logit soft-capping, alternating 4096 sliding window,
    # wide 256k tied vocab, head_dim 256.
    "gemma2-9b": ModelConfig(
        name="gemma2-9b",
        vocab_size=256_000,
        dim=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        ffn_hidden=14_336,
        head_dim=256,
        rope_theta=10_000.0,
        norm_eps=1e-6,
        max_seq_len=8192,
        act="gelu",
        norm_weight_offset=1.0,
        embed_scale=True,
        logit_softcap=30.0,
        attn_softcap=50.0,
        sliding_window=4096,
        sliding_pattern=2,
        query_pre_attn_scalar=224.0,  # dim / n_heads, NOT head_dim
        post_norms=True,
        tie_embeddings=True,
        params_b=9.24,
    ),
    # Tiny family configs for tests / CPU dev — same code paths, toy sizes.
    "tiny-qwen": ModelConfig(
        name="tiny-qwen",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        rope_theta=10_000.0,
        max_seq_len=512,
        qkv_bias=True,
        tie_embeddings=True,
        params_b=0.001,
    ),
    "tiny-qwen3": ModelConfig(
        name="tiny-qwen3",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        head_dim=64,  # explicit, != dim // n_heads = 32 (the qwen3 trap)
        rope_theta=10_000.0,
        max_seq_len=512,
        qk_norm=True,
        tie_embeddings=True,
        params_b=0.001,
    ),
    "tiny-mistral": ModelConfig(
        name="tiny-mistral",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        rope_theta=10_000.0,
        max_seq_len=512,
        sliding_window=64,
        sliding_pattern=1,
        tie_embeddings=True,
        params_b=0.001,
    ),
    "tiny-gemma": ModelConfig(
        name="tiny-gemma",
        vocab_size=512,
        dim=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=256,
        rope_theta=10_000.0,
        max_seq_len=512,
        act="gelu",
        norm_weight_offset=1.0,
        embed_scale=True,
        logit_softcap=30.0,
        attn_softcap=50.0,
        sliding_window=64,
        sliding_pattern=2,
        query_pre_attn_scalar=24.0,  # ≠ head_dim (32) so tests exercise it
        post_norms=True,
        tie_embeddings=True,
        params_b=0.001,
    ),
    # the published nomic_bert architecture (checkpoint config.json remains
    # authoritative when a weights dir is given): full-rotary rope, post-LN
    # LayerNorm, biasless gated SwiGLU, segment embeddings, mean pooling
    "nomic-embed-text": ModelConfig(
        name="nomic-embed-text",
        arch="encoder",
        vocab_size=30_528,
        dim=768,
        n_layers=12,
        n_heads=12,
        n_kv_heads=12,
        ffn_hidden=3072,
        rope_theta=10_000.0,
        norm_eps=1e-12,
        max_seq_len=8192,
        enc_norm="layer",
        enc_post_ln=True,
        enc_gated=True,
        enc_bias=False,
        type_vocab_size=2,
        pooling="mean",
        embed_dim=768,
        params_b=0.137,
    ),
    # Qwen3-Embedding-8B is architecturally a Qwen3 CAUSAL LM (HF exports
    # Qwen3ForCausalLM) pooled at the last token — it serves through
    # EmbeddingEngine's decoder path (models/llama.py:llama_encode), so real
    # safetensors load via the ordinary qwen3 weights mapping.
    "qwen3-embedding-8b": ModelConfig(
        name="qwen3-embedding-8b",
        vocab_size=151_936,
        dim=4096,
        n_layers=36,
        n_heads=32,
        n_kv_heads=8,
        ffn_hidden=12_288,
        head_dim=128,
        rope_theta=1_000_000.0,
        norm_eps=1e-6,
        max_seq_len=32_768,
        qk_norm=True,
        tie_embeddings=True,  # encoding never touches a head
        pooling="last",
        embed_dim=4096,
        params_b=7.57,
    ),
    "tiny-embed": ModelConfig(
        name="tiny-embed",
        arch="encoder",
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        ffn_hidden=128,
        rope_theta=10_000.0,
        max_seq_len=512,
        pooling="mean",
        embed_dim=64,
        params_b=0.0005,
    ),
}


def _compact(s: str) -> str:
    """Strip separators so "llama3.1:8b", "Llama-3.1-8B" and "llama_3.1_8b"
    all compare equal."""
    return re.sub(r"[-_.:\s]", "", s.lower())


def _encoder_config_from_hf(doc: dict, mt: str, name: str) -> ModelConfig:
    """Encoder (embedding) families: classic BERT and nomic_bert. The
    reference serves any embed model an Ollama host carries, inferring kind
    and metadata for unseen names (`discovery.go:482-560`); here an unseen
    encoder checkpoint dir becomes servable the same way."""
    import dataclasses

    if mt == "bert":
        act = str(doc.get("hidden_act") or "gelu").lower()
        if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh", "relu", "silu"):
            # a silently-substituted activation would embed garbage
            raise ValueError(f"unsupported hidden_act {act!r} for bert")
        dim = int(doc["hidden_size"])
        kw = dict(
            name=name or str(doc.get("_name_or_path") or mt),
            arch="encoder",
            vocab_size=int(doc["vocab_size"]),
            dim=dim,
            n_layers=int(doc["num_hidden_layers"]),
            n_heads=int(doc["num_attention_heads"]),
            n_kv_heads=int(doc["num_attention_heads"]),
            ffn_hidden=int(doc["intermediate_size"]),
            norm_eps=float(doc.get("layer_norm_eps") or 1e-12),
            max_seq_len=int(doc.get("max_position_embeddings") or 512),
            act=act,
            enc_norm="layer",
            enc_post_ln=True,
            enc_pos="learned",
            enc_gated=False,
            enc_bias=True,
            type_vocab_size=int(doc.get("type_vocab_size") or 0),
            pooling="mean",
            embed_dim=dim,
        )
    elif mt == "nomic_bert":
        # GPT-style key names (the nomic_bert config descends from GPT2Config)
        dim = int(doc.get("n_embd") or doc.get("hidden_size") or 768)
        n_heads = int(doc.get("n_head") or doc.get("num_attention_heads") or 12)
        act = str(doc.get("activation_function") or "swiglu").lower()
        if act not in ("swiglu", "geglu", "silu", "gelu", "gelu_new", "relu"):
            raise ValueError(f"unsupported activation_function {act!r} for nomic_bert")
        if bool(doc.get("prenorm", False)):
            # prenorm nomic needs a final-norm tensor whose checkpoint
            # naming we have no fixture for — fail loud, don't guess
            raise ValueError("unsupported nomic_bert prenorm=true (post-LN only)")
        rot_frac = float(doc.get("rotary_emb_fraction", 1.0) or 0.0)
        qkv_bias = bool(doc.get("qkv_proj_bias", True))
        for bias_key in ("mlp_fc1_bias", "mlp_fc2_bias"):
            if bias_key in doc and bool(doc[bias_key]) != qkv_bias:
                # one enc_bias flag covers every linear; a checkpoint with
                # biased attention but bias-free MLP (or vice versa) would
                # load-fail or silently zero-fill — refuse up front
                raise ValueError(
                    f"unsupported nomic_bert bias split: {bias_key}="
                    f"{bool(doc[bias_key])} but qkv_proj_bias={qkv_bias}"
                )
        kw = dict(
            name=name or str(doc.get("_name_or_path") or mt),
            arch="encoder",
            vocab_size=int(doc["vocab_size"]),
            dim=dim,
            n_layers=int(doc.get("n_layer") or doc.get("num_hidden_layers") or 12),
            n_heads=n_heads,
            n_kv_heads=n_heads,
            ffn_hidden=int(doc.get("n_inner") or doc.get("intermediate_size") or 4 * dim),
            rope_theta=float(doc.get("rotary_emb_base") or 10_000.0),
            norm_eps=float(doc.get("layer_norm_epsilon") or 1e-12),
            max_seq_len=int(doc.get("n_positions") or doc.get("max_position_embeddings") or 2048),
            # swiglu → silu gate; geglu → gelu gate; plain names pass through
            act=(
                "silu" if act in ("swiglu", "silu")
                else "gelu" if act == "geglu"
                else act
            ),
            enc_norm="layer",
            # prenorm=False (the nomic default) means post-LN residuals
            enc_post_ln=not bool(doc.get("prenorm", False)),
            enc_pos="rope" if rot_frac > 0 else "learned",
            enc_gated="glu" in act,
            enc_bias=qkv_bias,
            type_vocab_size=int(doc.get("type_vocab_size") or 0),
            pooling="mean",
            embed_dim=dim,
        )
        if 0.0 < rot_frac < 1.0:
            # partial-rotary needs a split rope application the encoder does
            # not implement — refuse rather than embed garbage
            raise ValueError(
                f"unsupported rotary_emb_fraction {rot_frac} for nomic_bert "
                "(only 0.0 or 1.0)"
            )
    else:  # pragma: no cover — dispatcher only sends the two types above
        raise ValueError(f"unsupported encoder model_type {mt!r}")
    cfg = ModelConfig(**kw)
    return dataclasses.replace(cfg, params_b=round(cfg.param_count() / 1e9, 3))


def config_from_hf(doc: dict, name: str = "") -> ModelConfig:
    """Build a ModelConfig from an HF checkpoint's config.json dict.

    The reference serves ANY model name its Ollama hosts carry, inferring
    catalog metadata for names it has never seen
    (`discovery.go:482-560`); this is the in-process analog — an arbitrary
    checkpoint directory becomes servable without a hand-written entry in
    MODEL_CONFIGS. Covers the implemented decoder families plus the
    BERT-family encoders; anything else raises ValueError (a silently-wrong
    architecture would produce garbage weights-load "successes")."""
    import dataclasses

    mt = str(doc.get("model_type", "")).lower()
    if mt in ("bert", "nomic_bert"):
        return _encoder_config_from_hf(doc, mt, name)
    n_heads = int(doc.get("num_attention_heads", 32))
    kw: dict = dict(
        name=name or str(doc.get("_name_or_path") or mt or "hf-model"),
        vocab_size=int(doc["vocab_size"]),
        dim=int(doc["hidden_size"]),
        n_layers=int(doc["num_hidden_layers"]),
        n_heads=n_heads,
        n_kv_heads=int(doc.get("num_key_value_heads") or n_heads),
        ffn_hidden=int(doc["intermediate_size"]),
        head_dim=int(doc.get("head_dim") or 0),
        rope_theta=float(doc.get("rope_theta") or 10_000.0),
        norm_eps=float(doc.get("rms_norm_eps") or 1e-5),
        max_seq_len=int(doc.get("max_position_embeddings") or 8192),
        tie_embeddings=bool(doc.get("tie_word_embeddings", False)),
    )
    rs = doc.get("rope_scaling") or {}
    rs = rs if isinstance(rs, dict) else {}
    rs_type = str(rs.get("rope_type") or rs.get("type") or "").lower()
    if rs_type == "linear":
        # position interpolation (LongChat-style): uniform frequency divide
        kw.update(rope_type="linear", rope_factor=float(rs.get("factor") or 1.0),
                  rope_orig_max=int(rs.get("original_max_position_embeddings") or 1))
    if mt == "llama":
        if rs_type == "llama3":
            kw.update(
                rope_type="llama3",
                rope_factor=float(rs.get("factor") or 1.0),
                rope_orig_max=int(rs.get("original_max_position_embeddings") or 0),
                llama3_low_freq_factor=float(rs.get("low_freq_factor") or 1.0),
                llama3_high_freq_factor=float(rs.get("high_freq_factor") or 4.0),
            )
    elif mt == "qwen2":
        kw["qkv_bias"] = True
    elif mt == "qwen3":
        # biases dropped in favor of per-head q/k RMSNorm; head_dim is
        # explicit and decouples from dim // n_heads below 8B
        kw["qk_norm"] = True
    elif mt == "mistral":
        kw["sliding_window"] = int(doc.get("sliding_window") or 0)
        kw["sliding_pattern"] = 1
    elif mt == "mixtral":
        kw["n_experts"] = int(doc["num_local_experts"])
        kw["experts_per_tok"] = int(doc.get("num_experts_per_tok") or 2)
    elif mt == "gemma2":
        kw.update(
            act="gelu",
            norm_weight_offset=1.0,
            embed_scale=True,
            logit_softcap=float(doc.get("final_logit_softcapping") or 0.0),
            attn_softcap=float(doc.get("attn_logit_softcapping") or 0.0),
            sliding_window=int(doc.get("sliding_window") or 0),
            sliding_pattern=2,
            query_pre_attn_scalar=float(doc.get("query_pre_attn_scalar") or 0.0),
            post_norms=True,
            tie_embeddings=True,
        )
    elif mt == "deepseek_v2":
        kw.update(
            arch="mla",
            n_kv_heads=1,  # latent cache poses as one KV head (models/mla.py)
            q_lora_rank=int(doc.get("q_lora_rank") or 0),
            kv_lora_rank=int(doc["kv_lora_rank"]),
            qk_rope_head_dim=int(doc["qk_rope_head_dim"]),
            qk_nope_head_dim=int(doc["qk_nope_head_dim"]),
            v_head_dim=int(doc["v_head_dim"]),
            n_experts=int(doc.get("n_routed_experts") or 0),
            experts_per_tok=int(doc.get("num_experts_per_tok") or 2),
            n_shared_experts=int(doc.get("n_shared_experts") or 0),
            moe_ffn_hidden=int(doc.get("moe_intermediate_size") or 0),
            first_dense_layers=int(doc.get("first_k_dense_replace") or 0),
            # HF DeepseekV2Config default is False (raw softmax gates)
            norm_topk_prob=bool(doc.get("norm_topk_prob", False)),
            routed_scaling_factor=float(doc.get("routed_scaling_factor") or 1.0),
        )
        if rs_type == "yarn":
            kw.update(
                rope_type="yarn",
                rope_factor=float(rs.get("factor") or 1.0),
                rope_orig_max=int(rs.get("original_max_position_embeddings") or 0),
                yarn_beta_fast=float(rs.get("beta_fast") or 32.0),
                yarn_beta_slow=float(rs.get("beta_slow") or 1.0),
                yarn_mscale=float(rs.get("mscale") or 0.0),
                yarn_mscale_all_dim=float(rs.get("mscale_all_dim") or 0.0),
            )
    else:
        raise ValueError(
            f"unsupported HF model_type {mt!r} "
            "(supported: llama, qwen2, qwen3, mistral, mixtral, gemma2, "
            "deepseek_v2, bert, nomic_bert)"
        )
    if rs_type and kw.get("rope_factor", 1.0) <= 1.0 and rs_type != "default":
        # a scaling recipe we did not apply: serving it with plain rope
        # would silently degrade past the original context window
        raise ValueError(f"unsupported rope_scaling type {rs_type!r} for {mt!r}")
    cfg = ModelConfig(**kw)
    return dataclasses.replace(cfg, params_b=round(cfg.param_count() / 1e9, 3))


def config_from_hf_dir(path: str, name: str = "") -> ModelConfig:
    """`config_from_hf` over a checkpoint directory's config.json. For
    encoder checkpoints a sentence-transformers `1_Pooling/config.json`
    beside the weights decides the pooling mode (config.json itself never
    records it)."""
    import dataclasses
    import json as _json
    import os as _os

    with open(_os.path.join(path, "config.json")) as f:
        cfg = config_from_hf(_json.load(f), name=name)
    pool_path = _os.path.join(path, "1_Pooling", "config.json")
    if cfg.arch == "encoder" and _os.path.isfile(pool_path):
        try:
            with open(pool_path) as f:
                pdoc = _json.load(f)
            if pdoc.get("pooling_mode_cls_token"):
                cfg = dataclasses.replace(cfg, pooling="cls")
            elif pdoc.get("pooling_mode_mean_tokens"):
                cfg = dataclasses.replace(cfg, pooling="mean")
        except Exception:
            pass  # malformed pooling config: keep the family default
    return cfg


def resolve_config(model, weights_dir: str = "") -> ModelConfig:
    """Config for a model name + optional checkpoint dir. A config.json in
    the checkpoint dir is AUTHORITATIVE (it describes the actual weights);
    the name-based catalog is the fallback — so any supported-family
    checkpoint serves without a hand-written MODEL_CONFIGS entry."""
    import logging
    import os as _os

    if not isinstance(model, str):
        return model
    if weights_dir and _os.path.isfile(_os.path.join(weights_dir, "config.json")):
        try:
            return config_from_hf_dir(weights_dir, name=model)
        except Exception as e:  # any malformed config.json → catalog fallback
            logging.getLogger("models").warning(
                "config.json in %s not usable (%s); falling back to catalog "
                "entry for %r", weights_dir, e, model,
            )
    return get_config(model)


def get_config(name: str) -> ModelConfig:
    key = name.lower().strip()
    if key in MODEL_CONFIGS:
        return MODEL_CONFIGS[key]
    # Accept common aliases ("llama3.1:8b", "meta-llama/Llama-3.1-8B-Instruct")
    # by comparing separator-stripped forms of the last path segment.
    ck = _compact(key.split("/")[-1])
    for cname, cfg in MODEL_CONFIGS.items():
        cc = _compact(cname)
        if cc == ck or cc in ck:
            return cfg
    if ("deepseek-v2" in key or "deepseek_v2" in key) and "lite" in key:
        return MODEL_CONFIGS["deepseek-v2-lite"]
    if "deepseek-r1" in key or "deepseek_r1" in key or "deepscaler" in key or "deepcoder" in key:
        # Ollama-style "deepseek-r1:1.5b" etc (reference tier seeds). Size
        # decides the BASE ARCHITECTURE: 1.5b/7b are Qwen2.5 distills, 8b
        # the llama distill. Other sizes (14b/32b/70b) have no config here
        # — falling through to the KeyError beats resolving to a
        # categorically wrong family (shape-mismatched weights, wrong vocab).
        if "1.5b" in key:
            return MODEL_CONFIGS["deepseek-r1-distill-qwen-1.5b"]
        if "7b" in key:
            return MODEL_CONFIGS["qwen2.5-7b"]  # R1-Distill-Qwen-7B base arch
        if "8b" in key:
            return MODEL_CONFIGS["deepseek-r1-distill-llama-8b"]
    if "llama" in key and "1b" in key:
        return MODEL_CONFIGS["llama-3.2-1b"]
    if "llama" in key:
        return MODEL_CONFIGS["llama-3.1-8b"]
    if "qwen" in key and "0.5b" in key:
        return MODEL_CONFIGS["qwen2.5-0.5b"]
    if "qwen" in key:
        return MODEL_CONFIGS["qwen2.5-7b"]
    if "mixtral" in key:
        return MODEL_CONFIGS["mixtral-8x7b"]
    if "mistral" in key:
        return MODEL_CONFIGS["mistral-7b"]
    if "gemma" in key:
        return MODEL_CONFIGS["gemma2-9b"]
    if "embed" in key:
        return MODEL_CONFIGS["nomic-embed-text"]
    raise KeyError(f"unknown model config: {name}")
