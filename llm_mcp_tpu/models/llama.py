"""Llama-family causal decoder, pure-JAX functional, designed for XLA/TPU.

Replaces the reference's delegated Ollama `/api/generate`/`/api/chat` execution
(`worker/llm_worker/main.py:222-243`, `core/internal/api/handlers.go:2427-2587`)
with an in-process model. TPU-first choices:

  - **Scan over layers** with stacked per-layer weights (leading dim L): one
    layer's XLA program compiled once, not L times — fast compiles and a small
    executable even at 32+ layers.
  - **Static shapes everywhere**: batch = engine slots, sequence = cache
    capacity; per-slot progress is carried in `lengths` (int32) and masking,
    never in array shapes — so jit compiles once per (batch, bucket).
  - **KV cache layout [L, B, Hkv, S, hd]**: heads before sequence so the
    trailing (S, hd) dims match native TPU (sublane, lane) tiling — the
    Pallas kernels stream K/V at full HBM bandwidth (kernels/attention.py).
  - **bfloat16 weights/activations, float32 softmax and logits.**
  - Sampling is fused into the decode step (see ops/sampling.py) so only [B]
    token ids leave the device per step.
  - `attn_impl="pallas"` routes attention through the fused flash kernels;
    "xla" uses einsum contractions (GQA) that XLA maps onto the MXU. Both
    paths share every other op, and tests assert they agree.

Layout conventions:
  params["layers"][name]: [L, ...] stacked weights
  KV cache: k, v: [L, B, Hkv, S, hd]
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..kernels.attention import decode_attention_cache, flash_prefill_attention
from ..ops.norms import rms_norm as _rms_norm
from ..ops.rope import rope_frequencies, apply_rope
from .configs import ModelConfig
from .moe import init_moe_layer_params, moe_ffn
from .quant import embed_lookup, logits_head, qdot

Params = dict[str, Any]


def init_llama_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random-init weights with fan-in scaling (used when no checkpoint is
    supplied; real weights load via models/weights.py)."""
    hd = cfg.resolved_head_dim
    L, D, H, Hkv, F, V = (
        cfg.n_layers,
        cfg.dim,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.ffn_hidden,
        cfg.vocab_size,
    )
    keys = jax.random.split(key, 8)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dtype)

    layers: Params = {
        "attn_norm": jnp.ones((L, D), dtype=dtype),
        "wq": w(keys[1], (L, D, H * hd), D),
        "wk": w(keys[2], (L, D, Hkv * hd), D),
        "wv": w(keys[3], (L, D, Hkv * hd), D),
        "wo": w(keys[4], (L, H * hd, D), H * hd),
        "ffn_norm": jnp.ones((L, D), dtype=dtype),
    }
    if cfg.n_experts:
        layers.update(init_moe_layer_params(cfg, keys[5], dtype))
    else:
        layers.update(
            {
                "w1": w(keys[5], (L, D, F), D),
                "w3": w(keys[6], (L, D, F), D),
                "w2": w(keys[7], (L, F, D), F),
            }
        )
    params: Params = {
        "embed": w(keys[0], (V, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dtype=dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = w(jax.random.fold_in(key, 99), (D, V), D)
    return params


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype: jnp.dtype = jnp.bfloat16
) -> dict[str, jnp.ndarray]:
    hd = cfg.resolved_head_dim
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_seq, hd)
    return {"k": jnp.zeros(shape, dtype=dtype), "v": jnp.zeros(shape, dtype=dtype)}


def _logits(cfg: ModelConfig, params: Params, h: jnp.ndarray) -> jnp.ndarray:
    h = _rms_norm(h, params["final_norm"], cfg.norm_eps)
    src = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    return logits_head(src, h, tied=cfg.tie_embeddings)


def prefill_masks(
    cfg: ModelConfig, S: int, lengths: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(cos [1,S,hd/2], sin, mask [B,S,S]) shared by all prefill layers."""
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    cos, sin = rope_frequencies(cfg.resolved_head_dim, cfg.rope_theta, positions)
    # Causal + padding mask, computed once: [B, S, S] would be big at long S,
    # so use [1, S, S] causal and fold padding via key-validity [B, 1, S].
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))[None]  # [1, S, S]
    valid_k = (jnp.arange(S)[None, :] < lengths[:, None])[:, None, :]  # [B, 1, S]
    return cos, sin, causal & valid_k


def prefill_layer(
    cfg: ModelConfig,
    lp: Params,  # this layer's weights (un-stacked)
    h: jnp.ndarray,  # [B, S, D]
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: jnp.ndarray,  # [B, S, S]
    lengths: jnp.ndarray,  # [B]
    attn_impl: str = "xla",
) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray]]:
    """One decoder layer over a full prompt. Shared by the scan in
    `llama_prefill` and the stage loop in parallel/pipeline.py."""
    B, S, _ = h.shape
    hd = cfg.resolved_head_dim
    H, Hkv = cfg.n_heads, cfg.n_kv_heads
    G = H // Hkv
    neg = jnp.float32(-1e30)

    x = _rms_norm(h, lp["attn_norm"], cfg.norm_eps)
    q = qdot(x, lp["wq"]).reshape(B, S, H, hd)
    k = qdot(x, lp["wk"]).reshape(B, S, Hkv, hd)
    v = qdot(x, lp["wv"]).reshape(B, S, Hkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    # Cache layout: heads before sequence (see module docstring).
    kh = k.transpose(0, 2, 1, 3)  # [B, Hkv, S, hd]
    vh = v.transpose(0, 2, 1, 3)

    if attn_impl == "pallas":
        qh = q.transpose(0, 2, 1, 3)  # [B, H, S, hd]
        ctx = flash_prefill_attention(qh, kh, vh, lengths)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, H * hd)
    else:
        qg = q.reshape(B, S, Hkv, G, hd)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
        scores = scores * (hd**-0.5)
        scores = jnp.where(mask[:, None, None, :, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v).reshape(B, S, H * hd)
    h = h + qdot(ctx, lp["wo"])

    x = _rms_norm(h, lp["ffn_norm"], cfg.norm_eps)
    if cfg.n_experts:
        h = h + moe_ffn(cfg, lp, x.reshape(B * S, -1)).reshape(B, S, -1)
    else:
        gate = jax.nn.silu(qdot(x, lp["w1"]))
        up = qdot(x, lp["w3"])
        h = h + qdot(gate * up, lp["w2"])
    return h, (kh, vh)


def llama_prefill(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32 (right-padded prompts)
    lengths: jnp.ndarray,  # [B] int32 true prompt lengths
    attn_impl: str = "xla",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Causal self-attention over fresh prompts (no past KV).

    Returns (last_logits [B, V] f32, k [L, B, Hkv, S, Dh], v [...]) — the
    prompt KV to be inserted into the engine cache at the request's slot.
    """
    B, S = tokens.shape
    h = embed_lookup(params["embed"], tokens)  # [B, S, D]
    cos, sin, mask = prefill_masks(cfg, S, lengths)

    def layer(h, lp):
        return prefill_layer(cfg, lp, h, cos, sin, mask, lengths, attn_impl)

    h, (ks, vs) = jax.lax.scan(layer, h, params["layers"])

    last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, D]
    return _logits(cfg, params, last), ks, vs


def llama_decode_step(
    cfg: ModelConfig,
    params: Params,
    cache_k: jnp.ndarray,  # [L, B, Hkv, S, Dh]
    cache_v: jnp.ndarray,
    tokens: jnp.ndarray,  # [B] int32 — last emitted token per slot
    lengths: jnp.ndarray,  # [B] int32 — position to write (tokens already in cache)
    attn_impl: str = "xla",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One batched autoregressive step for all slots.

    Writes this step's K/V at `lengths[b]`, attends over positions
    ≤ lengths[b], returns (logits [B, V] f32, new_cache_k, new_cache_v).
    Inactive slots simply produce garbage logits that the engine ignores —
    keeping the step shape-static (no data-dependent control flow under jit).
    """
    L, B, Hkv, S, hd = cache_k.shape
    H = cfg.n_heads
    G = H // Hkv

    h = embed_lookup(params["embed"], tokens)  # [B, D]
    cos, sin = rope_frequencies(hd, cfg.rope_theta, lengths)  # [B, hd/2]

    b_idx = jnp.arange(B)[:, None]  # [B, 1]
    h_idx = jnp.arange(Hkv)[None, :]  # [1, Hkv]
    w_idx = lengths[:, None]  # [B, 1] — broadcast with h_idx to [B, Hkv]
    key_pos = jnp.arange(S)[None, :]  # [1, S]
    attn_mask = key_pos <= lengths[:, None]  # [B, S]
    neg = jnp.float32(-1e30)

    # The full cache rides the layer scan as CARRY, not xs/ys: as ys the
    # scan would materialize a fresh [L, B, Hkv, S, hd] stack every step — a
    # full-cache HBM write per token (measured 17 ms/step at B=32 S=1024 for
    # a 1B model, ~3x the roofline). As carry, the only cache writes are the
    # per-layer one-token scatters, which XLA performs in place on the
    # donated buffers inside the loop; step time becomes weights + one cache
    # READ, which is the decode minimum.
    def layer(carry, lp):
        h, ck_all, cv_all, li = carry
        x = _rms_norm(h, lp["attn_norm"], cfg.norm_eps)
        q = qdot(x, lp["wq"]).reshape(B, H, hd)
        k = qdot(x, lp["wk"]).reshape(B, Hkv, hd)
        v = qdot(x, lp["wv"]).reshape(B, Hkv, hd)
        q = apply_rope(q[:, None], cos[:, None], sin[:, None])[:, 0]  # [B, H, hd]
        k = apply_rope(k[:, None], cos[:, None], sin[:, None])[:, 0]

        ck_all = ck_all.at[li, b_idx, h_idx, w_idx].set(k.astype(ck_all.dtype))
        cv_all = cv_all.at[li, b_idx, h_idx, w_idx].set(v.astype(cv_all.dtype))

        qg = q.reshape(B, Hkv, G, hd)
        if attn_impl == "pallas":
            # Kernel indexes the L axis itself (scalar prefetch): no
            # dynamic-slice copy of the layer's cache.
            ctx = decode_attention_cache(qg, ck_all, cv_all, li, lengths).reshape(
                B, H * hd
            )
        else:
            ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
            cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
            scores = jnp.einsum("bhgd,bhsd->bhgs", qg, ck).astype(jnp.float32)
            scores = scores * (hd**-0.5)
            scores = jnp.where(attn_mask[:, None, None, :], scores, neg)
            probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
            ctx = jnp.einsum("bhgs,bhsd->bhgd", probs, cv).reshape(B, H * hd)
        h = h + qdot(ctx, lp["wo"])

        x = _rms_norm(h, lp["ffn_norm"], cfg.norm_eps)
        if cfg.n_experts:
            h = h + moe_ffn(cfg, lp, x, capacity=B)  # dropless at decode
        else:
            gate = jax.nn.silu(qdot(x, lp["w1"]))
            up = qdot(x, lp["w3"])
            h = h + qdot(gate * up, lp["w2"])
        return (h, ck_all, cv_all, li + 1), None

    (h, new_k, new_v, _), _ = jax.lax.scan(
        layer, (h, cache_k, cache_v, jnp.int32(0)), params["layers"]
    )
    return _logits(cfg, params, h), new_k, new_v
