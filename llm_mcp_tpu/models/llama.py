"""Llama-family causal decoder, pure-JAX functional, designed for XLA/TPU.

Replaces the reference's delegated Ollama `/api/generate`/`/api/chat` execution
(`worker/llm_worker/main.py:222-243`, `core/internal/api/handlers.go:2427-2587`)
with an in-process model. TPU-first choices:

  - **Scan over layers** with stacked per-layer weights (leading dim L): one
    layer's XLA program compiled once, not L times — fast compiles and a small
    executable even at 32+ layers.
  - **Static shapes everywhere**: batch = engine slots, sequence = cache
    capacity; per-slot progress is carried in `lengths` (int32) and masking,
    never in array shapes — so jit compiles once per (batch, bucket).
  - **KV cache layout [L, B, Hkv, S, hd]**: heads before sequence so the
    trailing (S, hd) dims match native TPU (sublane, lane) tiling — the
    Pallas kernels stream K/V at full HBM bandwidth (kernels/attention.py).
  - **bfloat16 weights/activations, float32 softmax and logits.**
  - Sampling is fused into the decode step (see ops/sampling.py) so only [B]
    token ids leave the device per step.
  - `attn_impl="pallas"` routes attention through the fused flash kernels;
    "xla" uses einsum contractions (GQA) that XLA maps onto the MXU. Both
    paths share every other op, and tests assert they agree.

Layout conventions:
  params["layers"][name]: [L, ...] stacked weights
  KV cache: k, v: [L, B, Hkv, S, hd]
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..kernels.attention import (
    append_kv_bf16,
    append_kv_q8,
    decode_attend_bf16,
    decode_attend_q8,
    flash_prefill_attention,
    paged_gather,
    ragged_prefill_attend_bf16,
    ragged_prefill_attend_q8,
)
from ..ops.norms import rms_norm as _rms_norm
from ..ops.rope import rope_tables, apply_rope
from .configs import ModelConfig
from .moe import init_moe_layer_params, moe_ffn
from .quant import (
    embed_lookup,
    logits_head,
    pack_scales,
    qdot,
    scale_pack_width,
    scan_unroll,
)

Params = dict[str, Any]


def init_llama_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16,
    _dispatch: bool = True,
) -> Params:
    """Random-init weights with fan-in scaling (used when no checkpoint is
    supplied; real weights load via models/weights.py). MLA configs
    (kv_lora_rank > 0) dispatch to models/mla.py, which reuses this body
    for the shared embed/FFN/norm structure via _dispatch=False."""
    if _dispatch and cfg.kv_lora_rank:
        from .mla import init_mla_params

        return init_mla_params(cfg, key, dtype=dtype)
    hd = cfg.resolved_head_dim
    L, D, H, Hkv, F, V = (
        cfg.n_layers,
        cfg.dim,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.ffn_hidden,
        cfg.vocab_size,
    )
    keys = jax.random.split(key, 8)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dtype)

    # norm weights init to 1 - offset so an offset-norm family (Gemma's
    # x * (1 + w)) starts at the same identity scale as plain RMSNorm.
    norm_init = jnp.full((L, D), 1.0 - cfg.norm_weight_offset, dtype=dtype)
    layers: Params = {"attn_norm": norm_init, "ffn_norm": norm_init}
    if not cfg.kv_lora_rank:
        # GQA projections — MLA configs (reached with _dispatch=False from
        # init_mla_params) build their factorized attention instead; at
        # 8B-class shapes the discarded GQA weights would be a ~4 GB
        # init-time transient
        layers.update(
            {
                "wq": w(keys[1], (L, D, H * hd), D),
                "wk": w(keys[2], (L, D, Hkv * hd), D),
                "wv": w(keys[3], (L, D, Hkv * hd), D),
                "wo": w(keys[4], (L, H * hd, D), H * hd),
            }
        )
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype=dtype)
        layers["bk"] = jnp.zeros((L, Hkv * hd), dtype=dtype)
        layers["bv"] = jnp.zeros((L, Hkv * hd), dtype=dtype)
    if cfg.qk_norm:
        # Qwen3 per-head q/k RMSNorm: one [hd] weight vector per layer
        layers["q_norm"] = jnp.ones((L, hd), dtype=dtype)
        layers["k_norm"] = jnp.ones((L, hd), dtype=dtype)
    if cfg.post_norms:
        layers["post_attn_norm"] = norm_init
        layers["post_ffn_norm"] = norm_init
    if cfg.n_experts:
        layers.update(init_moe_layer_params(cfg, keys[5], dtype))
    else:
        layers.update(
            {
                "w1": w(keys[5], (L, D, F), D),
                "w3": w(keys[6], (L, D, F), D),
                "w2": w(keys[7], (L, F, D), F),
            }
        )
    params: Params = {
        "embed": w(keys[0], (V, D), D),
        "layers": layers,
        "final_norm": jnp.full((D,), 1.0 - cfg.norm_weight_offset, dtype=dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = w(jax.random.fold_in(key, 99), (D, V), D)
    return params


def init_kv_cache(
    cfg: ModelConfig,
    batch: int,
    max_seq: int,
    dtype: jnp.dtype = jnp.bfloat16,
    quantized: bool = False,
) -> dict[str, Any]:
    """KV cache buffers. `quantized=True` stores int8 payloads with
    per-(token, head) scales — decode is cache-bandwidth-bound once weights
    are int8, so halving KV bytes buys ~25-40% step time at 8B/B≥32 and
    doubles the (batch × context) that fits beside the weights.

    Quantized GQA entries use the FUSED single-payload layout:

        cache["k"] = {"q": int8 [L, B, 2*Hkv + p, S, hd],
                      "s": dtype [L, B, 2*Hkv, S]}
        cache["v"] = {}   (V rides cache["k"]'s head axis)

    Payload head rows [0, Hkv) are K, [Hkv, 2*Hkv) are V, and — when the
    scale bytes fit one head row (p = 1, `models/quant.py:scale_pack_width`)
    — head 2*Hkv carries the per-position dequant scales BIT-PACKED into
    int8 lanes. The fusion is what lets the blocked decode kernel issue ONE
    DMA per (row, block) cell instead of the r05 layout's four (kq/ks/vq/vs
    as separate arrays — kernels/attention.py:_attend_q8_blocked_kernel);
    the plain "s" array is dual-written for every consumer that wants
    arithmetic scales (whole-S kernel, XLA einsum paths, chunked prefill).
    The seq axis stays axis 3 in both members — the engine's slot machinery
    (inserts, parking, snapshots) indexes [:, slot, :, pos] unchanged.

    Plain entries are a bare [L,B,Hkv,S,hd] array per side. All forms flow
    through `llama_decode_step` (jit treats them as pytrees).

    MLA configs store latents instead (models/mla.py:init_mla_cache) in the
    same (k, v) pair convention; quantized=True there stores int8 latents
    (a further capacity trade on top of the latent cache's ~3.6x size
    advantage; decode pays a dequant-then-dot on the XLA path)."""
    if cfg.kv_lora_rank:
        from .mla import init_mla_cache

        return init_mla_cache(cfg, batch, max_seq, dtype=dtype, quantized=quantized)
    hd = cfg.resolved_head_dim
    Hkv = cfg.n_kv_heads
    shape = (cfg.n_layers, batch, Hkv, max_seq, hd)
    if quantized:
        p = scale_pack_width(Hkv, hd, dtype)
        return {
            "k": {
                "q": jnp.zeros(
                    (cfg.n_layers, batch, 2 * Hkv + p, max_seq, hd), dtype=jnp.int8
                ),
                "s": jnp.zeros(
                    (cfg.n_layers, batch, 2 * Hkv, max_seq), dtype=dtype
                ),
            },
            "v": {},
        }
    return {"k": jnp.zeros(shape, dtype=dtype), "v": jnp.zeros(shape, dtype=dtype)}


def quantize_kv(kv: jnp.ndarray, scale_dtype=None) -> dict[str, jnp.ndarray]:
    """Quantize a bf16 K or V block to the int8 cache form over its last
    (head_dim) axis: per-(…, token, head) symmetric scales, like the cache's
    write path. Used when inserting prefill KV into a quantized cache."""
    f = kv.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1)
    s = amax / 127.0
    q = jnp.where(
        s[..., None] > 0, jnp.round(f / jnp.maximum(s, 1e-30)[..., None]), 0.0
    ).astype(jnp.int8)
    return {"q": q, "s": s.astype(scale_dtype or kv.dtype)}


def fuse_prompt_kv(
    kh: jnp.ndarray,  # [..., Hkv, S, hd] bf16 K rows (head-major)
    vh: jnp.ndarray,  # [..., Hkv, S, hd]
    scale_dtype=None,
) -> dict[str, jnp.ndarray]:
    """Quantize a prompt's K/V rows into the FUSED cache entry
    (`init_kv_cache`): one int8 payload carrying K heads | V heads | the
    optional bit-packed scale pseudo-head, plus the plain "s" scales. The
    engine's cache "v" member is the empty dict — callers pair the returned
    dict with `{}`."""
    hd = kh.shape[-1]
    Hkv = kh.shape[-3]
    kq = quantize_kv(kh, scale_dtype=scale_dtype)
    vq = quantize_kv(vh, scale_dtype=scale_dtype)
    s = jnp.concatenate([kq["s"], vq["s"]], axis=-2)  # [..., 2*Hkv, S]
    pay = jnp.concatenate([kq["q"], vq["q"]], axis=-3)  # [..., 2*Hkv, S, hd]
    if scale_pack_width(Hkv, hd, s.dtype):
        pay = jnp.concatenate([pay, pack_scales(s, hd)], axis=-3)
    return {"q": pay, "s": s}


def _cache_shape(cache) -> tuple[int, ...]:
    return cache["q"].shape if isinstance(cache, dict) else cache.shape


def _norm(cfg: ModelConfig, x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """RMSNorm with the family's weight convention: llama scales by w,
    Gemma by (1 + w) (norm_weight_offset)."""
    if cfg.norm_weight_offset:
        w = w + jnp.asarray(cfg.norm_weight_offset, dtype=w.dtype)
    return _rms_norm(x, w, cfg.norm_eps)


def _act(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.gelu(x) if cfg.act == "gelu" else jax.nn.silu(x)


def _softcap(x: jnp.ndarray, cap: float) -> jnp.ndarray:
    return jnp.tanh(x / cap) * cap if cap else x


def _qkv(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    """Q/K/V projections (+ family bias / qk-norm) on [..., D] activations;
    outputs stay flat [..., H*hd] / [..., Hkv*hd] — callers reshape for
    their layout. This is the single seam every attention path (prefill,
    chunked prefill, both decode steps) goes through, so per-family query/
    key transforms live here exactly once."""
    if "wqkv" in lp:
        # single-chip fused projection (models/quant.py:fuse_layer_weights):
        # one qdot quantizes the activation row once and reads one contiguous
        # int8 weight block instead of three — bitwise-identical outputs,
        # fewer per-matmul dispatch/epilogue round trips in the layer scan
        hd = cfg.resolved_head_dim
        nq, nk = cfg.n_heads * hd, cfg.n_kv_heads * hd
        qkv = qdot(x, lp["wqkv"])
        if cfg.qkv_bias:
            qkv = qkv + lp["bqkv"]
        q = qkv[..., :nq]
        k = qkv[..., nq : nq + nk]
        v = qkv[..., nq + nk :]
    else:
        q = qdot(x, lp["wq"])
        k = qdot(x, lp["wk"])
        v = qdot(x, lp["wv"])
        if cfg.qkv_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
    if cfg.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim, applied pre-rope. Weights
        # are one [hd] vector per layer, shared across heads.
        hd = cfg.resolved_head_dim
        q = _rms_norm(
            q.reshape(*q.shape[:-1], -1, hd), lp["q_norm"], cfg.norm_eps
        ).reshape(q.shape)
        k = _rms_norm(
            k.reshape(*k.shape[:-1], -1, hd), lp["k_norm"], cfg.norm_eps
        ).reshape(k.shape)
    return q, k, v


def _attn_residual(cfg: ModelConfig, lp: Params, ctx: jnp.ndarray, h: jnp.ndarray):
    """Output projection (+ optional post-attention norm) and residual add."""
    out = qdot(ctx, lp["wo"])
    if cfg.post_norms:
        out = _norm(cfg, out, lp["post_attn_norm"])
    return h + out


def _ffn_residual(
    cfg: ModelConfig,
    lp: Params,
    h: jnp.ndarray,
    moe_capacity: int = 0,
    moe_valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """The FFN half of a decoder layer (pre-norm, MoE or gated-MLP, optional
    post-norm, residual add) on [..., D] activations — shared by prefill,
    chunked prefill, and decode so layer semantics live in one place."""
    x = _norm(cfg, h, lp["ffn_norm"])
    # dispatch on THIS LAYER's params, not cfg: DeepSeek-style models carry
    # a dense prologue (params["dense_layers"], cfg.first_dense_layers)
    # through the same layer function as their MoE stack
    if "router" in lp:
        lead = x.shape[:-1]
        flat = x.reshape(-1, x.shape[-1])
        fvalid = moe_valid.reshape(-1) if moe_valid is not None else None
        out = (
            moe_ffn(cfg, lp, flat, capacity=moe_capacity, valid=fvalid)
            if moe_capacity
            else moe_ffn(cfg, lp, flat, valid=fvalid)
        )
        out = out.reshape(*lead, -1)
    elif "w13" in lp:
        # single-chip fused gate|up (models/quant.py:fuse_layer_weights) —
        # same w8a8 epilogue-fusion move as wqkv
        g13 = qdot(x, lp["w13"])
        F = g13.shape[-1] // 2
        gate = _act(cfg, g13[..., :F])
        out = qdot(gate * g13[..., F:], lp["w2"])
    else:
        gate = _act(cfg, qdot(x, lp["w1"]))
        up = qdot(x, lp["w3"])
        out = qdot(gate * up, lp["w2"])
    if cfg.post_norms:
        out = _norm(cfg, out, lp["post_ffn_norm"])
    return h + out


def layer_windows(cfg: ModelConfig) -> jnp.ndarray:
    """Per-layer attention window sizes, [L] int32 (0 = global attention).

    `sliding_pattern=1` → every layer sliding (Mistral); `=p` → every p-th
    layer global, the rest sliding (Gemma2 alternation with p=2)."""
    p = max(cfg.sliding_pattern, 1)
    wins = [
        cfg.sliding_window if cfg.sliding_window and (p == 1 or li % p != p - 1) else 0
        for li in range(cfg.n_layers)
    ]
    return jnp.asarray(wins, dtype=jnp.int32)


def _embed_in(cfg: ModelConfig, params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
    h = embed_lookup(params["embed"], tokens)
    if cfg.embed_scale:
        h = h * jnp.asarray(cfg.dim**0.5, dtype=h.dtype)
    return h


def _logits(cfg: ModelConfig, params: Params, h: jnp.ndarray) -> jnp.ndarray:
    h = _norm(cfg, h, params["final_norm"])
    src = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    return _softcap(logits_head(src, h, tied=cfg.tie_embeddings), cfg.logit_softcap)


def prefill_masks(
    cfg: ModelConfig, S: int, lengths: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(cos [1,S,hd/2], sin, mask [B,S,S]) shared by all prefill layers."""
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    cos, sin = rope_tables(cfg, cfg.resolved_head_dim, positions)
    # Causal + padding mask, computed once: [B, S, S] would be big at long S,
    # so use [1, S, S] causal and fold padding via key-validity [B, 1, S].
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))[None]  # [1, S, S]
    valid_k = (jnp.arange(S)[None, :] < lengths[:, None])[:, None, :]  # [B, 1, S]
    return cos, sin, causal & valid_k


def prefill_layer(
    cfg: ModelConfig,
    lp: Params,  # this layer's weights (un-stacked)
    h: jnp.ndarray,  # [B, S, D]
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: jnp.ndarray,  # [B, S, S]
    lengths: jnp.ndarray,  # [B]
    attn_impl: str = "xla",
    window: jnp.ndarray | int = 0,  # this layer's sliding window (0 = global)
) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray]]:
    """One decoder layer over a full prompt. Shared by the scan in
    `llama_prefill` and the stage loop in parallel/pipeline.py."""
    B, S, _ = h.shape
    hd = cfg.resolved_head_dim
    H, Hkv = cfg.n_heads, cfg.n_kv_heads
    G = H // Hkv
    neg = jnp.float32(-1e30)
    window = jnp.asarray(window, dtype=jnp.int32)

    x = _norm(cfg, h, lp["attn_norm"])
    q, k, v = _qkv(cfg, lp, x)
    q = q.reshape(B, S, H, hd)
    k = k.reshape(B, S, Hkv, hd)
    v = v.reshape(B, S, Hkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    # Cache layout: heads before sequence (see module docstring).
    kh = k.transpose(0, 2, 1, 3)  # [B, Hkv, S, hd]
    vh = v.transpose(0, 2, 1, 3)

    if attn_impl == "pallas":
        qh = q.transpose(0, 2, 1, 3)  # [B, H, S, hd]
        ctx = flash_prefill_attention(
            qh,
            kh,
            vh,
            lengths,
            window=window,
            softcap=cfg.attn_softcap,
            scale=cfg.attn_scale,
        )
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, H * hd)
    else:
        qg = q.reshape(B, S, Hkv, G, hd)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
        scores = _softcap(scores * cfg.attn_scale, cfg.attn_softcap)
        m = mask
        if cfg.sliding_window:
            # q_pos - k_pos < window; window == 0 disables (global layer)
            diff = jnp.arange(S)[:, None] - jnp.arange(S)[None, :]  # [S, S]
            m = m & ((window == 0) | (diff < window))[None]
        scores = jnp.where(m[:, None, None, :, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v).reshape(B, S, H * hd)
    h = _attn_residual(cfg, lp, ctx, h)
    h = _ffn_residual(
        cfg, lp, h,
        moe_valid=jnp.arange(S, dtype=jnp.int32)[None, :] < lengths[:, None],
    )
    return h, (kh, vh)


def llama_prefill(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32 (right-padded prompts)
    lengths: jnp.ndarray,  # [B] int32 true prompt lengths
    attn_impl: str = "xla",
    quant_kv: bool = False,
) -> tuple[jnp.ndarray, Any, Any]:
    """Causal self-attention over fresh prompts (no past KV).

    Returns (last_logits [B, V] f32, k [L, B, Hkv, S, Dh], v [...]) — the
    prompt KV to be inserted into the engine cache at the request's slot.

    `quant_kv=True` quantizes each layer's K/V INSIDE the scan into the
    FUSED cache entry form (`fuse_prompt_kv` — K|V|packed-scale payload +
    plain scales, paired with `{}` for v), so the stacked ys are int8
    pytrees and the full bf16 prompt KV never materializes in HBM — at 8B a
    batch-8 × 256-bucket admission would otherwise stack ~1 GB of bf16 KV
    before the engine's quantize step, enough memory pressure to collapse
    serving throughput. Fusing here means every engine insert path receives
    cache-layout-ready rows and never re-derives the packed scale bytes.
    """
    if cfg.kv_lora_rank:  # MLA family: latent cache, query-blocked prefill
        from .mla import mla_prefill

        return mla_prefill(cfg, params, tokens, lengths, quant_kv=quant_kv)
    B, S = tokens.shape
    h = _embed_in(cfg, params, tokens)  # [B, S, D]
    cos, sin, mask = prefill_masks(cfg, S, lengths)

    def layer(h, xs):
        lp, win = xs
        h, (kh, vh) = prefill_layer(
            cfg, lp, h, cos, sin, mask, lengths, attn_impl, window=win
        )
        if quant_kv:
            return h, (fuse_prompt_kv(kh, vh), {})
        return h, (kh, vh)

    h, (ks, vs) = jax.lax.scan(layer, h, (params["layers"], layer_windows(cfg)))

    last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, D]
    return _logits(cfg, params, last), ks, vs


def llama_encode(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32 right-padded
    lengths: jnp.ndarray,  # [B] int32 true lengths
    attn_impl: str = "xla",
) -> jnp.ndarray:
    """The causal decoder run as a TEXT ENCODER: hidden state at each
    sequence's last valid position, final-normed and L2-normalized —
    [B, D] unit vectors. This is how decoder-architecture embedding models
    (Qwen3-Embedding: a Qwen3 causal LM with last-token pooling) serve
    through EmbeddingEngine; the bidirectional mean/cls-pooling families
    stay on models/embedder.py. The reference only reaches any embedder
    through Ollama's /api/embed proxy (handlers.go:1942-2015)."""
    h = _embed_in(cfg, params, tokens)  # [B, S, D]
    cos, sin, mask = prefill_masks(cfg, tokens.shape[1], lengths)

    def layer(h, xs):
        lp, win = xs
        h, _ = prefill_layer(
            cfg, lp, h, cos, sin, mask, lengths, attn_impl, window=win
        )
        return h, None

    h, _ = jax.lax.scan(layer, h, (params["layers"], layer_windows(cfg)))
    last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, D]
    e = _norm(cfg, last, params["final_norm"]).astype(jnp.float32)
    return e / jnp.maximum(jnp.linalg.norm(e, axis=-1, keepdims=True), 1e-9)


def _decode_step_q8(
    cfg: ModelConfig,
    params: Params,
    cache_k: dict,
    cache_v: dict,
    tokens: jnp.ndarray,  # [Ba] int32 (compact batch when slot_ids is given)
    lengths: jnp.ndarray,  # [Ba] int32
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, dict, dict]:
    """Decode step for the int8 cache on the pallas path.

    Structure matters more than arithmetic here: carrying the cache through
    the layer scan and scattering each layer's one-token K/V row costs XLA a
    full cache-payload copy PER LAYER (14.2 ms of a 37.5 ms step at 8B
    B=112 S=1024 — the single largest line item in the decode budget).
    Instead the cache is a scan-invariant operand read by `decode_attend_q8`
    (which overrides this step's position with the exact in-register
    vectors, so correctness never depends on the append having happened),
    the per-layer K/V stack out as scan ys ([L, Ba, Hkv, hd] — 3.7 MB), and
    ONE `append_kv_q8` call rewrites just the 32-row tiles in place.
    Measured: 37.5 -> ~24 ms/step.

    With `slot_ids` the batch axis is COMPACT: row i computes the forward
    pass for cache row slot_ids[i] (slot compaction — at low occupancy the
    weights pass and sampling shrink to the active rows instead of paying
    for every parked slot; the kernels follow the indirection via scalar
    prefetch, so cache traffic also shrinks on the blocked path).
    """
    # fused cache: axis 2 of "q" is 2*Hkv + p, not Hkv — take Hkv from cfg
    L, B, _, S, hd = _cache_shape(cache_k)
    Hkv = cfg.n_kv_heads
    Ba = tokens.shape[0]
    H = cfg.n_heads
    h = _embed_in(cfg, params, tokens)  # [Ba, D]
    cos, sin = rope_tables(cfg, hd, lengths)  # [Ba, hd/2]

    def layer(carry, xs):
        lp, win = xs
        h, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        q, k, v = _qkv(cfg, lp, x)
        q = q.reshape(Ba, H, hd)
        k = k.reshape(Ba, Hkv, hd)
        v = v.reshape(Ba, Hkv, hd)
        q = apply_rope(q[:, None], cos[:, None], sin[:, None])[:, 0]
        k = apply_rope(k[:, None], cos[:, None], sin[:, None])[:, 0]
        qg = q.reshape(Ba, Hkv, H // Hkv, hd)
        ctx = decode_attend_q8(
            qg, k, v, cache_k, cache_v, li, lengths,
            slot_ids=slot_ids, scale=cfg.attn_scale,
            block_tables=None if paged is None else paged["tbl"],
            pool_k=None if paged is None else paged["k"],
        ).reshape(Ba, H * hd)
        h = _attn_residual(cfg, lp, ctx, h)
        h = _ffn_residual(cfg, lp, h, moe_capacity=Ba)
        return (h, li + 1), (k, v)

    (h, _), (knew, vnew) = jax.lax.scan(
        layer,
        (h, jnp.int32(0)),
        (params["layers"], layer_windows(cfg)),
        unroll=scan_unroll(),
    )
    new_k, new_v = append_kv_q8(cache_k, cache_v, knew, vnew, lengths, slot_ids=slot_ids)
    return _logits(cfg, params, h), new_k, new_v


def _decode_step_bf16(
    cfg: ModelConfig,
    params: Params,
    cache_k: jnp.ndarray,  # [L, B, Hkv, S, hd]
    cache_v: jnp.ndarray,
    tokens: jnp.ndarray,  # [Ba] int32 (compact batch when slot_ids is given)
    lengths: jnp.ndarray,  # [Ba] int32
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decode step for the bf16 cache on the pallas path — the structure
    that made the q8 path fast (`_decode_step_q8`), applied to the split
    bf16 cache: the cache rides the layer scan as a scan-INVARIANT operand
    (no per-layer scatter), `decode_attend_bf16` overrides this step's
    position with the exact in-register vectors, the per-layer K/V rows
    stack out as scan ys, and ONE `append_kv_bf16` call rewrites just the
    16-row tiles in place after the scan. Replaces the old in-scan sliced
    kernel (the since-removed `decode_attention_cache` + per-layer carry
    scatter) that `resolve_decode_impl` used to reject in favor of XLA."""
    L, B, Hkv, S, hd = _cache_shape(cache_k)
    Ba = tokens.shape[0]
    H = cfg.n_heads
    h = _embed_in(cfg, params, tokens)  # [Ba, D]
    cos, sin = rope_tables(cfg, hd, lengths)  # [Ba, hd/2]

    def layer(carry, xs):
        lp, win = xs
        h, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        q, k, v = _qkv(cfg, lp, x)
        q = q.reshape(Ba, H, hd)
        k = k.reshape(Ba, Hkv, hd)
        v = v.reshape(Ba, Hkv, hd)
        q = apply_rope(q[:, None], cos[:, None], sin[:, None])[:, 0]
        k = apply_rope(k[:, None], cos[:, None], sin[:, None])[:, 0]
        qg = q.reshape(Ba, Hkv, H // Hkv, hd)
        ctx = decode_attend_bf16(
            qg, k, v, cache_k, cache_v, li, lengths,
            slot_ids=slot_ids, scale=cfg.attn_scale,
            block_tables=None if paged is None else paged["tbl"],
            pool_k=None if paged is None else paged["k"],
            pool_v=None if paged is None else paged["v"],
        ).reshape(Ba, H * hd)
        h = _attn_residual(cfg, lp, ctx, h)
        h = _ffn_residual(cfg, lp, h, moe_capacity=Ba)
        return (h, li + 1), (k, v)

    (h, _), (knew, vnew) = jax.lax.scan(
        layer,
        (h, jnp.int32(0)),
        (params["layers"], layer_windows(cfg)),
        unroll=scan_unroll(),
    )
    new_k, new_v = append_kv_bf16(
        cache_k, cache_v, knew, vnew, lengths, slot_ids=slot_ids
    )
    return _logits(cfg, params, h), new_k, new_v


def llama_prefill_chunk_batch(
    cfg: ModelConfig,
    params: Params,
    cache_k: Any,  # [L, B, Hkv, S, hd] engine cache (or int8 {"q","s"} pytree)
    cache_v: Any,
    tokens: jnp.ndarray,  # [A, C] int32 — right-padded chunks, one per slot
    slots: jnp.ndarray,  # [A] int32 — engine slots (distinct, or duplicated row 0 padding)
    starts: jnp.ndarray,  # [A] int32 — absolute position of each chunk's first token
    nvalid: jnp.ndarray,  # [A] int32 — valid tokens per chunk
    skey: int = 0,  # STATIC bound on the PAST key range (0 = whole S); >= max(starts)
    all_logits: bool = False,  # STATIC: logits at every chunk position, not just the last
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, Any, Any]:
    """Batched chunked prefill: one bounded chunk for up to A slots' prompts
    in a single dispatch, written straight into the engine cache.

    Three TPU-first structural choices (each measured against the naive
    form on a v5e chip at 8B):

    - **Batched over slots**: the chunk weight pass dominates chunk cost
      (~65 ms at 8B int8); A prompts amortize it A-fold. A serial admission
      path starves the continuous batch — most slots sit idle waiting to
      prefill (measured 102 tok/s vs ~1.9 k tok/s decode capacity at B=64).
    - **Read-past-then-write**: the chunk attends the slot's PAST rows
      [0, starts) read from the pre-write cache, and its own K/V from
      registers (exact bf16, even when the cache is int8 — the same
      semantics as the decode kernel's current-position override). All cache
      writes happen after the reads: write-after-read updates in place,
      while the read-after-write form costs XLA defensive copies.
    - **Static buckets everywhere**: C and `skey` are compile-time buckets
      (pow2), positions/slots are traced scalars — one executable per
      (A, C, skey) serves every admission forever.

    Padding rows past `nvalid` in a ragged final chunk are written but never
    attended (causal mask; valid q rows never reach garbage columns) and are
    overwritten in place by later decode steps. Engine interleaving:
    executor/engine.py:_stage_prefill_group (token-budget scheduler,
    executor/scheduler.py). The reference never faces any of
    this — it proxies Ollama (`core/internal/api/handlers.go:2427-2587`).

    Returns (logits [A, V] f32 at each row's last valid position — or
    [A, C, V] at every position when `all_logits` (the speculative-decoding
    verify path scores each drafted token against the position before it) —
    new_cache_k, new_cache_v).
    """
    if cfg.kv_lora_rank:  # MLA family: absorbed chunked prefill over latents
        from .mla import mla_prefill_chunk_batch

        return mla_prefill_chunk_batch(
            cfg, params, cache_k, cache_v, tokens, slots, starts, nvalid,
            skey=skey, all_logits=all_logits, paged=paged,
        )
    quantized = isinstance(cache_k, dict)
    # fused quantized cache: axis 2 of "q" is 2*Hkv + p — take Hkv from cfg
    L, B, _, S, hd = _cache_shape(cache_k)
    Hkv = cfg.n_kv_heads
    H = cfg.n_heads
    G = H // Hkv
    A, C = tokens.shape
    Sk = min(skey, S) if skey else S
    neg = jnp.float32(-1e30)
    slots = jnp.asarray(slots, dtype=jnp.int32)
    starts = jnp.asarray(starts, dtype=jnp.int32)

    # Block-indirect past reads: gather each slot's PAST rows through its
    # block table (shared prefix blocks resolve to pool rows) instead of a
    # contiguous slice. Only the first ceil(Sk/bt) table entries matter —
    # the gather is bounded by the same static skey bucket as before.
    ptbl = None
    if paged is not None:
        nbs_full = paged["tbl"].shape[1]
        bt = S // nbs_full
        nsel = max(1, -(-Sk // bt))
        ptbl = jnp.take(paged["tbl"], slots, axis=0)[:, :nsel]

    h = _embed_in(cfg, params, tokens)  # [A, C, D]
    q_pos = starts[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]  # [A, C]
    cos, sin = rope_tables(cfg, hd, q_pos)  # [A, C, hd/2]
    key_pos = jnp.arange(Sk, dtype=jnp.int32)  # [Sk]
    # past segment: cache rows strictly before each chunk's start
    past_mask = key_pos[None, None, :] < starts[:, None, None]  # [A, 1|C, Sk]
    past_mask = jnp.broadcast_to(past_mask, (A, C, Sk))
    # self segment: causal within the chunk
    c_idx = jnp.arange(C, dtype=jnp.int32)
    self_mask = jnp.broadcast_to(
        (c_idx[None, :] <= c_idx[:, None])[None], (A, C, C)
    )

    def layer(carry, xs):
        lp, win = xs
        h, ck_all, cv_all, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q.reshape(A, C, H, hd), cos, sin)
        k = apply_rope(k.reshape(A, C, Hkv, hd), cos, sin)
        v = v.reshape(A, C, Hkv, hd)
        kh = k.transpose(0, 2, 1, 3)  # [A, Hkv, C, hd]
        vh = v.transpose(0, 2, 1, 3)
        qg = q.reshape(A, C, Hkv, G, hd)

        # ---- reads first: the past rows from the PRE-write cache ----
        if quantized:
            # FUSED layout: K heads [0,Hkv) and V heads [Hkv,2Hkv) share one
            # payload — one slice per slot covers both (the packed-scale
            # pseudo-head past 2*Hkv is never read here; the plain "s" rows
            # carry the arithmetic scales)
            if ptbl is not None:
                pays = paged_gather(
                    jax.lax.dynamic_index_in_dim(ck_all["q"], li, 0, keepdims=False),
                    jax.lax.dynamic_index_in_dim(paged["k"]["q"], li, 0, keepdims=False),
                    ptbl, nbs=nbs_full,
                )[:, : 2 * Hkv, :Sk]  # [A, 2*Hkv, Sk, hd] int8
                srows = paged_gather(
                    jax.lax.dynamic_index_in_dim(ck_all["s"], li, 0, keepdims=False),
                    jax.lax.dynamic_index_in_dim(paged["k"]["s"], li, 0, keepdims=False),
                    ptbl, nbs=nbs_full,
                )[:, : 2 * Hkv, :Sk]  # [A, 2*Hkv, Sk]
            else:
                pays = jnp.stack(
                    [
                        jax.lax.dynamic_slice(
                            ck_all["q"], (li, slots[a], 0, 0, 0), (1, 1, 2 * Hkv, Sk, hd)
                        )[0, 0]
                        for a in range(A)
                    ]
                )  # [A, 2*Hkv, Sk, hd] int8
                srows = jnp.stack(
                    [
                        jax.lax.dynamic_slice(
                            ck_all["s"], (li, slots[a], 0, 0), (1, 1, 2 * Hkv, Sk)
                        )[0, 0]
                        for a in range(A)
                    ]
                )  # [A, 2*Hkv, Sk]
            krows, vrows = pays[:, :Hkv], pays[:, Hkv:]
            ksr, vsr = srows[:, :Hkv], srows[:, Hkv:]
        elif ptbl is not None:
            krows = paged_gather(
                jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(paged["k"], li, 0, keepdims=False),
                ptbl, nbs=nbs_full,
            )[:, :, :Sk]
            vrows = paged_gather(
                jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(paged["v"], li, 0, keepdims=False),
                ptbl, nbs=nbs_full,
            )[:, :, :Sk]
        else:
            krows = jnp.stack(
                [
                    jax.lax.dynamic_slice(
                        ck_all, (li, slots[a], 0, 0, 0), (1, 1, Hkv, Sk, hd)
                    )[0, 0]
                    for a in range(A)
                ]
            )  # [A, Hkv, Sk, hd]
            vrows = jnp.stack(
                [
                    jax.lax.dynamic_slice(
                        cv_all, (li, slots[a], 0, 0, 0), (1, 1, Hkv, Sk, hd)
                    )[0, 0]
                    for a in range(A)
                ]
            )

        # past scores (dequant post-dot when the cache is int8)
        s_past = jnp.einsum(
            "achgd,ahsd->ahgcs", qg, krows.astype(h.dtype)
        ).astype(jnp.float32)
        if quantized:
            s_past = s_past * ksr.astype(jnp.float32)[:, :, None, None, :]
        # self scores: exact, from in-register bf16 K
        s_self = jnp.einsum("achgd,ahtd->ahgct", qg, kh).astype(jnp.float32)
        s_past = _softcap(s_past * cfg.attn_scale, cfg.attn_softcap)
        s_self = _softcap(s_self * cfg.attn_scale, cfg.attn_softcap)

        pm, sm = past_mask, self_mask
        if cfg.sliding_window:
            pm = pm & (
                (win == 0)
                | (q_pos[:, :, None] - key_pos[None, None, :] < win)
            )
            sm = sm & ((win == 0) | (c_idx[None, :] - c_idx[:, None] > -win))
        s_past = jnp.where(pm[:, None, None, :, :], s_past, neg)
        s_self = jnp.where(sm[:, None, None, :, :], s_self, neg)

        # joint softmax over [past | self]
        s = jnp.concatenate([s_past, s_self], axis=-1)  # [A, Hkv, G, C, Sk+C]
        probs = jax.nn.softmax(s, axis=-1)
        p_past, p_self = probs[..., :Sk], probs[..., Sk:]
        if quantized:
            p_past = p_past * vsr.astype(jnp.float32)[:, :, None, None, :]
        ctx = jnp.einsum(
            "ahgcs,ahsd->achgd", p_past.astype(h.dtype), vrows.astype(h.dtype)
        ) + jnp.einsum("ahgct,ahtd->achgd", p_self.astype(h.dtype), vh)
        ctx = ctx.reshape(A, C, H * hd)
        h = _attn_residual(cfg, lp, ctx, h)
        h = _ffn_residual(
            cfg, lp, h, moe_valid=c_idx[None, :] < nvalid[:, None]
        )

        # ---- writes last: in-place (write-after-read) ----
        if quantized:
            # write the chunk's rows in cache layout: fused payload
            # (K|V|packed scales) + plain scales, so later readers — decode
            # kernels included — see a consistent fused entry
            fused = fuse_prompt_kv(kh, vh, scale_dtype=ck_all["s"].dtype)
            for a in range(A):
                ck_all = {
                    "q": jax.lax.dynamic_update_slice(
                        ck_all["q"], fused["q"][a][None, None], (li, slots[a], 0, starts[a], 0)
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        ck_all["s"], fused["s"][a][None, None], (li, slots[a], 0, starts[a])
                    ),
                }
        else:
            for a in range(A):
                ck_all = jax.lax.dynamic_update_slice(
                    ck_all, kh[a][None, None].astype(ck_all.dtype), (li, slots[a], 0, starts[a], 0)
                )
                cv_all = jax.lax.dynamic_update_slice(
                    cv_all, vh[a][None, None].astype(cv_all.dtype), (li, slots[a], 0, starts[a], 0)
                )
        return (h, ck_all, cv_all, li + 1), None

    (h, new_k, new_v, _), _ = jax.lax.scan(
        layer,
        (h, cache_k, cache_v, jnp.int32(0)),
        (params["layers"], layer_windows(cfg)),
    )
    if all_logits:
        return _logits(cfg, params, h), new_k, new_v  # [A, C, V]
    last = jnp.take_along_axis(
        h, (nvalid - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [A, D]
    return _logits(cfg, params, last), new_k, new_v


def llama_prefill_chunk(
    cfg: ModelConfig,
    params: Params,
    cache_k: Any,
    cache_v: Any,
    tokens: jnp.ndarray,  # [C] int32 — single slot's chunk
    slot: jnp.ndarray,
    start: jnp.ndarray,
    nvalid: jnp.ndarray,
    skey: int = 0,
    paged: dict | None = None,
) -> tuple[jnp.ndarray, Any, Any]:
    """Single-slot wrapper over `llama_prefill_chunk_batch` (A=1)."""
    return llama_prefill_chunk_batch(
        cfg,
        params,
        cache_k,
        cache_v,
        tokens[None, :],
        jnp.asarray(slot, dtype=jnp.int32)[None],
        jnp.asarray(start, dtype=jnp.int32)[None],
        jnp.asarray(nvalid, dtype=jnp.int32)[None],
        skey=skey,
        paged=paged,
    )


def llama_prefill_chunk_ragged(
    cfg: ModelConfig,
    params: Params,
    cache_k: Any,  # [L, B, Hkv, S, hd] engine cache (or fused int8 {"q","s"})
    cache_v: Any,
    tokens: jnp.ndarray,  # [T] int32 — PACKED chunks, rows back-to-back
    rowids: jnp.ndarray,  # [T] int32 — descriptor row per token, SORTED
    #   ascending; pad tokens carry rowid == R
    positions: jnp.ndarray,  # [T] int32 — absolute rope/write position per
    #   token; pad tokens carry S (their cache scatters DROP)
    slots: jnp.ndarray,  # [R] int32 — engine slot per descriptor row
    starts: jnp.ndarray,  # [R] int32 — cached-prefix length per row
    last_idx: jnp.ndarray,  # [R] int32 — packed index of each row's LAST
    #   token this chunk (0 for unused rows — never sampled by the engine)
    skey: int = 0,  # STATIC past bound for the XLA arm (kernel arm ignores
    #   it — past trips are data-dependent, so 0 keeps ONE executable)
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, Any, Any]:
    """Ragged chunked prefill: the packed-descriptor twin of
    `llama_prefill_chunk_batch`. Instead of [A, C] bucket-padded rows, up to
    R rows' chunks pack back-to-back into one [T] token buffer — compute is
    spent on real tokens only, and because T and R are static while every
    descriptor (rowids, positions, offsets, starts, tables) is data, ONE
    executable per (T, layout) serves every fill mix where the bucketed path
    mints one per (A, bucket, skey). Attention runs through the ragged
    paged-native kernels (`kernels/attention.py:ragged_prefill_attend_*`):
    the cached prefix streams block-indirect through the PR 10 tables, the
    chunk's own K/V stays exact bf16 from registers, and masks derive from
    the packed row boundaries. Same read-past-then-write discipline as the
    bucketed path; writes are positional scatters (`mode="drop"` — pad
    tokens carry position S and vanish, the parked-slot OOB convention).

    Sliding-window and softcap families are NOT supported — the engine's
    ragged eligibility gate routes them to the bucketed path.

    Returns (logits [R, V] f32 at each row's `last_idx` token, new_k, new_v).
    """
    if cfg.kv_lora_rank:  # MLA family: absorbed ragged prefill over latents
        from .mla import mla_prefill_chunk_ragged

        return mla_prefill_chunk_ragged(
            cfg, params, cache_k, cache_v, tokens, rowids, positions,
            slots, starts, last_idx, skey=skey, paged=paged,
        )
    if cfg.sliding_window or cfg.attn_softcap:
        raise NotImplementedError(
            "ragged prefill covers global-attention, no-softcap families; "
            "the engine gates others to the bucketed path"
        )
    quantized = isinstance(cache_k, dict)
    L, B, _, S, hd = _cache_shape(cache_k)
    Hkv = cfg.n_kv_heads
    H = cfg.n_heads
    G = H // Hkv
    T = tokens.shape[0]
    R = slots.shape[0]
    slots = jnp.asarray(slots, dtype=jnp.int32)
    starts = jnp.asarray(starts, dtype=jnp.int32)
    rowids = jnp.asarray(rowids, dtype=jnp.int32)
    positions = jnp.asarray(positions, dtype=jnp.int32)
    # packed row boundaries from the sorted rowids: offsets[r] = first packed
    # index of row r; offsets[R] = total real tokens
    offsets = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            jnp.sum(
                (rowids[None, :] < jnp.arange(1, R + 1, dtype=jnp.int32)[:, None]),
                axis=1,
                dtype=jnp.int32,
            ),
        ]
    )  # [R+1]
    wslot = slots[jnp.clip(rowids, 0, R - 1)]  # [T] write slot per token
    moe_valid = rowids < R  # [T]
    btbl = paged["tbl"] if paged is not None else None

    h = _embed_in(cfg, params, tokens)  # [T, D]
    cos, sin = rope_tables(cfg, hd, positions)  # [T, hd/2]

    def layer(carry, lp):
        h, ck_all, cv_all, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q.reshape(T, H, hd), cos, sin)
        k = apply_rope(k.reshape(T, Hkv, hd), cos, sin)
        v = v.reshape(T, Hkv, hd)
        qg = q.reshape(T, Hkv, G, hd)

        # ---- reads first: ragged attention over [cached past | packed self]
        if quantized:
            ctx = ragged_prefill_attend_q8(
                qg, k, v, ck_all, li, rowids, offsets, slots, starts,
                scale=cfg.attn_scale, skey=skey, block_tables=btbl,
                pool=paged["k"] if paged is not None else None,
            )
        else:
            ctx = ragged_prefill_attend_bf16(
                qg, k, v, ck_all, cv_all, li, rowids, offsets, slots, starts,
                scale=cfg.attn_scale, skey=skey, block_tables=btbl,
                pool_k=paged["k"] if paged is not None else None,
                pool_v=paged["v"] if paged is not None else None,
            )
        ctx = ctx.reshape(T, H * hd)
        h = _attn_residual(cfg, lp, ctx, h)
        h = _ffn_residual(cfg, lp, h, moe_valid=moe_valid)

        # ---- writes last: positional scatter, pads (position S) DROP ----
        # (paging keeps writes at identity arena homes — COW re-homing is
        # host-side ledger machinery, so the scatter needs no tables)
        if quantized:
            fused = fuse_prompt_kv(
                k.transpose(1, 0, 2), v.transpose(1, 0, 2),
                scale_dtype=ck_all["s"].dtype,
            )  # {"q": [2*Hkv+p, T, hd], "s": [2*Hkv, T]}
            ck_all = {
                "q": ck_all["q"].at[li, wslot, :, positions].set(
                    fused["q"].transpose(1, 0, 2), mode="drop"
                ),
                "s": ck_all["s"].at[li, wslot, :, positions].set(
                    fused["s"].T, mode="drop"
                ),
            }
        else:
            ck_all = ck_all.at[li, wslot, :, positions].set(
                k.astype(ck_all.dtype), mode="drop"
            )
            cv_all = cv_all.at[li, wslot, :, positions].set(
                v.astype(cv_all.dtype), mode="drop"
            )
        return (h, ck_all, cv_all, li + 1), None

    (h, new_k, new_v, _), _ = jax.lax.scan(
        layer, (h, cache_k, cache_v, jnp.int32(0)), params["layers"]
    )
    last = jnp.take(h, jnp.clip(last_idx, 0, T - 1), axis=0)  # [R, D]
    return _logits(cfg, params, last), new_k, new_v


def llama_decode_step(
    cfg: ModelConfig,
    params: Params,
    cache_k: jnp.ndarray,  # [L, B, Hkv, S, Dh]
    cache_v: jnp.ndarray,
    tokens: jnp.ndarray,  # [Ba] int32 — last emitted token per batch row
    lengths: jnp.ndarray,  # [Ba] int32 — position to write (tokens already in cache)
    attn_impl: str = "xla",
    slot_ids: jnp.ndarray | None = None,  # [Ba] int32 cache rows (None = 1:1)
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand —
    #   block-indirect reads through executor/physical.py tables (None =
    #   contiguous). Writes are UNTOUCHED: decode always appends at private
    #   positions, and private blocks live at their identity homes.
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One batched autoregressive step for all slots.

    Writes this step's K/V at `lengths[b]`, attends over positions
    ≤ lengths[b], returns (logits [Ba, V] f32, new_cache_k, new_cache_v).
    Inactive slots simply produce garbage logits that the engine ignores —
    keeping the step shape-static (no data-dependent control flow under jit).

    With `slot_ids` the batch is COMPACT: row i serves cache row
    slot_ids[i] (reads attend that row, the K/V append scatters into it).
    The forward pass then sizes to the active rows only — the engine's slot
    compaction (executor/engine.py:_dispatch_decode) uses this so parked slots
    stop costing weights-pass FLOPs and sampling work.

    The caches may be int8-quantized ({"q", "s"} pytrees — see
    `init_kv_cache`): scales then fold into the attention einsums post-dot
    (QK scores scale by k's per-token scale; v's folds into the probs), so
    the HBM read is int8 payload + 1/head_dim of scales.
    """
    if cfg.kv_lora_rank:  # MLA family: absorbed decode over the latent cache
        from .mla import mla_decode_step

        return mla_decode_step(
            cfg, params, cache_k, cache_v, tokens, lengths,
            slot_ids=slot_ids, attn_impl=attn_impl, paged=paged,
        )
    quantized = isinstance(cache_k, dict)
    # fused quantized cache: axis 2 of "q" is 2*Hkv + p — take Hkv from cfg
    L, B, _, S, hd = _cache_shape(cache_k)
    Hkv = cfg.n_kv_heads
    Ba = tokens.shape[0]
    H = cfg.n_heads
    G = H // Hkv

    # Sliding windows / score softcaps aren't implemented in the pallas
    # decode kernels; those families take the XLA path. Both cache dtypes
    # otherwise share the scan-invariant + post-scan-append structure:
    # int8 routes to the s8-MXU hybrid (decode_attend_q8), bf16 to its twin
    # (decode_attend_bf16) — both take cfg.attn_scale and follow slot_ids,
    # so query_pre_attn_scalar families and compacted batches stay on the
    # kernel path now.
    if attn_impl == "pallas" and (cfg.sliding_window or cfg.attn_softcap):
        attn_impl = "xla"

    if quantized and attn_impl == "pallas":
        # The TPU hot path takes a different structure: cache is a
        # scan-INVARIANT operand (no per-layer scatter — measured 14.2 ms of
        # a 37.5 ms step at 8B B=112) and the append happens once post-scan
        # via the in-place tile-rewrite kernel (kernels/attention.py:
        # append_kv_q8). decode_attend_q8 is built for pre-append caches: it
        # overrides position w with the exact new vectors.
        return _decode_step_q8(
            cfg, params, cache_k, cache_v, tokens, lengths,
            slot_ids=slot_ids, paged=paged,
        )
    if attn_impl == "pallas" and not quantized:
        # same structure for the bf16 cache (new: it used to take the
        # in-scan sliced kernel, which lost to XLA — the restructure wins)
        return _decode_step_bf16(
            cfg, params, cache_k, cache_v, tokens, lengths,
            slot_ids=slot_ids, paged=paged,
        )

    h = _embed_in(cfg, params, tokens)  # [Ba, D]
    cos, sin = rope_tables(cfg, hd, lengths)  # [Ba, hd/2]

    # row i of the compact batch scatters/gathers cache row rows[i]
    rows = jnp.arange(B, dtype=jnp.int32) if slot_ids is None else slot_ids
    b_idx = rows[:, None]  # [Ba, 1]
    h_idx = jnp.arange(Hkv)[None, :]  # [1, Hkv]
    w_idx = lengths[:, None]  # [Ba, 1] — broadcast with h_idx to [Ba, Hkv]
    key_pos = jnp.arange(S)[None, :]  # [1, S]
    attn_mask = key_pos <= lengths[:, None]  # [Ba, S]
    neg = jnp.float32(-1e30)

    def rowsel(x):
        # gather the compact batch's cache rows for the einsum attention
        # paths (identity when uncompacted — XLA elides the arange take)
        return x if slot_ids is None else jnp.take(x, slot_ids, axis=0)

    ptbl = None if paged is None else jnp.take(paged["tbl"], rows, axis=0)

    def csel(x_all, li, pool_all):
        # layer-select + row-select; block-indirect through the compacted
        # table when physical paging is live (subsumes rowsel: table row i
        # resolves slot rows[i]'s blocks, private ones to identity homes)
        x = jax.lax.dynamic_index_in_dim(x_all, li, 0, keepdims=False)
        if ptbl is None:
            return rowsel(x)
        p = jax.lax.dynamic_index_in_dim(pool_all, li, 0, keepdims=False)
        return paged_gather(x, p, ptbl)

    # The full cache rides the layer scan as CARRY, not xs/ys: as ys the
    # scan would materialize a fresh [L, B, Hkv, S, hd] stack every step — a
    # full-cache HBM write per token (measured 17 ms/step at B=32 S=1024 for
    # a 1B model, ~3x the roofline). As carry, the only cache writes are the
    # per-layer one-token scatters, which XLA performs in place on the
    # donated buffers inside the loop; step time becomes weights + one cache
    # READ, which is the decode minimum.
    def layer(carry, xs):
        lp, win = xs
        h, ck_all, cv_all, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        q, k, v = _qkv(cfg, lp, x)
        q = q.reshape(Ba, H, hd)
        k = k.reshape(Ba, Hkv, hd)
        v = v.reshape(Ba, Hkv, hd)
        q = apply_rope(q[:, None], cos[:, None], sin[:, None])[:, 0]  # [Ba, H, hd]
        k = apply_rope(k[:, None], cos[:, None], sin[:, None])[:, 0]

        qg = q.reshape(Ba, Hkv, G, hd)
        # Append this step's K/V row to the carry, quantizing into the FUSED
        # layout when the cache is int8. The scatter happens BEFORE the
        # attention read: write-after-read on the carried buffer would cost
        # XLA a full-cache defensive copy (~10 ms at 8B B=64).
        if quantized:
            kq = quantize_kv(k, scale_dtype=ck_all["s"].dtype)
            vq = quantize_kv(v, scale_dtype=ck_all["s"].dtype)
            s_new = jnp.concatenate([kq["s"], vq["s"]], axis=1)  # [Ba, 2*Hkv]
            pay = jnp.concatenate([kq["q"], vq["q"]], axis=1)  # [Ba, 2*Hkv, hd]
            if ck_all["q"].shape[2] > 2 * Hkv:
                # keep the packed pseudo-head consistent too: snapshots /
                # path switches must see one coherent fused entry
                pay = jnp.concatenate(
                    [pay, pack_scales(s_new[..., None], hd)[..., 0, :]], axis=1
                )
            hf_idx = jnp.arange(pay.shape[1])[None, :]
            hs_idx = jnp.arange(2 * Hkv)[None, :]
            ck_all = {
                "q": ck_all["q"].at[li, b_idx, hf_idx, w_idx].set(pay),
                "s": ck_all["s"].at[li, b_idx, hs_idx, w_idx].set(s_new),
            }
        else:
            ck_all = ck_all.at[li, b_idx, h_idx, w_idx].set(k.astype(ck_all.dtype))
            cv_all = cv_all.at[li, b_idx, h_idx, w_idx].set(v.astype(cv_all.dtype))

        if quantized:
            payl = csel(ck_all["q"], li, None if paged is None else paged["k"]["q"])
            ssl = csel(ck_all["s"], li, None if paged is None else paged["k"]["s"])
            ck, cv = payl[:, :Hkv], payl[:, Hkv : 2 * Hkv]
            ks, vs = ssl[:, :Hkv], ssl[:, Hkv:]
            # int8 K dot in compute dtype; per-key-token dequant scales the
            # SCORES (cheap [Ba,Hkv,G,S] multiply), not the K payload
            scores = jnp.einsum("bhgd,bhsd->bhgs", qg, ck.astype(h.dtype)).astype(
                jnp.float32
            ) * ks.astype(jnp.float32)[:, :, None, :]
            scores = _softcap(scores * cfg.attn_scale, cfg.attn_softcap)
            m = attn_mask
            if cfg.sliding_window:
                m = m & ((win == 0) | (key_pos > (lengths[:, None] - win)))
            scores = jnp.where(m[:, None, None, :], scores, neg)
            probs = jax.nn.softmax(scores, axis=-1)
            # v's dequant folds into the probs before the PV dot
            probs = (probs * vs.astype(jnp.float32)[:, :, None, :]).astype(h.dtype)
            ctx = jnp.einsum("bhgs,bhsd->bhgd", probs, cv.astype(h.dtype)).reshape(
                Ba, H * hd
            )
        else:
            ck = csel(ck_all, li, None if paged is None else paged["k"])
            cv = csel(cv_all, li, None if paged is None else paged["v"])
            scores = jnp.einsum("bhgd,bhsd->bhgs", qg, ck).astype(jnp.float32)
            scores = _softcap(scores * cfg.attn_scale, cfg.attn_softcap)
            m = attn_mask
            if cfg.sliding_window:
                m = m & ((win == 0) | (key_pos > (lengths[:, None] - win)))
            scores = jnp.where(m[:, None, None, :], scores, neg)
            probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
            ctx = jnp.einsum("bhgs,bhsd->bhgd", probs, cv).reshape(Ba, H * hd)
        h = _attn_residual(cfg, lp, ctx, h)
        h = _ffn_residual(cfg, lp, h, moe_capacity=Ba)  # dropless at decode
        return (h, ck_all, cv_all, li + 1), None

    (h, new_k, new_v, _), _ = jax.lax.scan(
        layer,
        (h, cache_k, cache_v, jnp.int32(0)),
        (params["layers"], layer_windows(cfg)),
        unroll=scan_unroll(),
    )
    return _logits(cfg, params, h), new_k, new_v
