"""Mixture-of-Experts FFN with GShard-style capacity dispatch (TPU-first).

The reference has no MoE (no model execution at all — Ollama serves Mixtral
et al. as opaque names in the catalog, `discovery.go:526-551`). Here MoE is a
real sharded subsystem so Mixtral-class models run in-process.

TPU-first design choices:

  - **Dense dispatch via one-hot matmuls** (Switch/GShard formulation): the
    token→expert routing is expressed as two einsums against a [T, E, C]
    dispatch tensor instead of gather/scatter — everything is static-shaped,
    maps onto the MXU, and GSPMD turns the dispatch einsums into the
    all-to-all when experts are sharded on the `ep` mesh axis.
  - **Stacked expert weights** `[L, E, D, F]`: one batched matmul per layer
    (`ecd,edf->ecf`) instead of E separate matmuls — large MXU tiles, and the
    `E` dim shards cleanly with `P("ep")`.
  - **Capacity-bounded**: each expert processes at most C tokens per step
    (`C = ceil(T·k/E · capacity_factor)`); overflow tokens are dropped from
    that expert (their gate mass is simply lost, residual carries them) —
    the standard trade that keeps shapes static under jit.
  - Router math in float32 (softmax over expert logits), expert FFN in the
    model dtype.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from .configs import ModelConfig


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    """Static per-expert token capacity for a T-token step."""
    c = math.ceil(n_tokens * cfg.experts_per_tok / cfg.n_experts * cfg.capacity_factor)
    return max(1, min(c, n_tokens))


def moe_dispatch(
    cfg: ModelConfig,
    router_logits: jnp.ndarray,
    capacity: int,
    valid: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Build (dispatch [T, E, C] model-dtype 0/1, combine [T, E, C] f32 gates).

    Top-k routing with normalized gates; position-in-expert assigned by
    cumulative count with slot-0 priority (GShard), tokens beyond capacity
    dropped.

    `valid` ([T] bool) excludes rows from routing entirely: bucket-padding
    tokens must not consume expert capacity ahead of real tokens (the
    cumsum priority is positional, so garbage rows earlier in the flattened
    batch would otherwise steal slots and change real tokens' outputs).
    """
    T, E = router_logits.shape
    k = cfg.experts_per_tok
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T, E]
    top_g, top_i = jax.lax.top_k(probs, k)  # [T, k]
    if cfg.norm_topk_prob and k > 1:
        top_g = top_g / jnp.sum(top_g, axis=-1, keepdims=True)  # renormalize
    elif cfg.routed_scaling_factor != 1.0:
        # DeepSeek-V2 gate convention: raw softmax mass, scaled
        top_g = top_g * cfg.routed_scaling_factor

    dispatch = jnp.zeros((T, E, capacity), dtype=jnp.float32)
    combine = jnp.zeros((T, E, capacity), dtype=jnp.float32)
    prev_count = jnp.zeros((E,), dtype=jnp.int32)
    for j in range(k):  # k is tiny and static (1-2 typically)
        mask_j = jax.nn.one_hot(top_i[:, j], E, dtype=jnp.int32)  # [T, E]
        if valid is not None:
            mask_j = mask_j * valid.astype(jnp.int32)[:, None]
        pos_j = jnp.cumsum(mask_j, axis=0) - 1 + prev_count[None, :]  # [T, E]
        prev_count = prev_count + jnp.sum(mask_j, axis=0)
        keep = (pos_j < capacity) & (mask_j > 0)  # [T, E]
        slot = jax.nn.one_hot(jnp.clip(pos_j, 0, capacity - 1), capacity)  # [T,E,C]
        sel = jnp.where(keep[..., None], slot, 0.0)
        dispatch = dispatch + sel
        combine = combine + sel * top_g[:, j][:, None, None]
    return dispatch, combine


def moe_ffn(
    cfg: ModelConfig,
    lp: dict[str, Any],
    x: jnp.ndarray,
    capacity: int | None = None,
    valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Sparse FFN over flattened tokens x: [T, D] → [T, D].

    lp holds this layer's "router" [D, E], "w1e"/"w3e" [E, D, F],
    "w2e" [E, F, D] (sliced from the stacked [L, ...] tree by the caller's
    scan). With `P("ep")` on the E dim, GSPMD inserts the token all-to-all
    around the batched expert matmuls.

    `capacity=T` makes the layer dropless — decode passes this (a [B, E, B]
    dispatch over engine slots is tiny, and dropping tokens at decode time
    would silently degrade generations); prefill uses the capacity factor to
    bound the batched expert matmul at large T.
    """
    T, D = x.shape
    C = capacity if capacity is not None else expert_capacity(cfg, T)
    logits = jnp.einsum("td,de->te", x, lp["router"])  # router in f32 below
    dispatch, combine = moe_dispatch(cfg, logits, C, valid=valid)

    xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E, C, D]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, lp["w1e"]))
    up = jnp.einsum("ecd,edf->ecf", xe, lp["w3e"])
    ye = jnp.einsum("ecf,efd->ecd", gate * up, lp["w2e"])  # [E, C, D]
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), ye)  # [T, D]
    if "w1s" in lp:
        # DeepSeek shared experts: a dense always-on gated MLP added to the
        # routed output (never dropped, no dispatch). qdot so int8-quantized
        # shared weights flow through like any dense linear.
        from .quant import qdot

        sg = jax.nn.silu(qdot(x, lp["w1s"]))
        y = y + qdot(sg * qdot(x, lp["w3s"]), lp["w2s"])
    return y


def init_moe_layer_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype, n_layers: int | None = None
) -> dict[str, jnp.ndarray]:
    """Stacked [L, ...] MoE weights (Mixtral-style all-MoE, or the MoE block
    of a DeepSeek first-dense split — `n_layers` overrides the stack depth).

    Routed experts use cfg.moe_ffn_hidden when set (DeepSeek's routed width
    is far narrower than its dense layer-0 FFN); `n_shared_experts` adds the
    always-on shared gated MLP (hidden = n_shared x moe width)."""
    L = cfg.n_layers if n_layers is None else n_layers
    D, E = cfg.dim, cfg.n_experts
    F = cfg.moe_ffn_hidden or cfg.ffn_hidden
    keys = jax.random.split(key, 7)

    def w(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)
        ).astype(dtype)

    out = {
        "router": w(keys[0], (L, D, E), D),
        "w1e": w(keys[1], (L, E, D, F), D),
        "w3e": w(keys[2], (L, E, D, F), D),
        "w2e": w(keys[3], (L, E, F, D), F),
    }
    if cfg.n_shared_experts:
        Fs = cfg.n_shared_experts * F
        out["w1s"] = w(keys[4], (L, D, Fs), D)
        out["w3s"] = w(keys[5], (L, D, Fs), D)
        out["w2s"] = w(keys[6], (L, Fs, D), Fs)
    return out
