"""Mixture-of-Experts FFN with GShard-style capacity dispatch (TPU-first).

The reference has no MoE (no model execution at all — Ollama serves Mixtral
et al. as opaque names in the catalog, `discovery.go:526-551`). Here MoE is a
real sharded subsystem so Mixtral-class models run in-process.

TPU-first design choices:

  - **Dense dispatch via one-hot matmuls** (Switch/GShard formulation): the
    token→expert routing is expressed as two einsums against a [T, E, C]
    dispatch tensor instead of gather/scatter — everything is static-shaped,
    maps onto the MXU, and GSPMD turns the dispatch einsums into the
    all-to-all when experts are sharded on the `ep` mesh axis.
  - **Stacked expert weights** `[L, E, D, F]`: one batched matmul per layer
    (`ecd,edf->ecf`) instead of E separate matmuls — large MXU tiles, and the
    `E` dim shards cleanly with `P("ep")`.
  - **Capacity-bounded**: each expert processes at most C tokens per step
    (`C = ceil(T·k/E · capacity_factor)`); overflow tokens are dropped from
    that expert (their gate mass is simply lost, residual carries them) —
    the standard trade that keeps shapes static under jit.
  - Router math in float32 (softmax over expert logits), expert FFN in the
    model dtype.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from .configs import ModelConfig


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    """Static per-expert token capacity for a T-token step."""
    c = math.ceil(n_tokens * cfg.experts_per_tok / cfg.n_experts * cfg.capacity_factor)
    return max(1, min(c, n_tokens))


def moe_dispatch(
    cfg: ModelConfig, router_logits: jnp.ndarray, capacity: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Build (dispatch [T, E, C] model-dtype 0/1, combine [T, E, C] f32 gates).

    Top-k routing with normalized gates; position-in-expert assigned by
    cumulative count with slot-0 priority (GShard), tokens beyond capacity
    dropped.
    """
    T, E = router_logits.shape
    k = cfg.experts_per_tok
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T, E]
    top_g, top_i = jax.lax.top_k(probs, k)  # [T, k]
    top_g = top_g / jnp.sum(top_g, axis=-1, keepdims=True)  # renormalize gates

    dispatch = jnp.zeros((T, E, capacity), dtype=jnp.float32)
    combine = jnp.zeros((T, E, capacity), dtype=jnp.float32)
    prev_count = jnp.zeros((E,), dtype=jnp.int32)
    for j in range(k):  # k is tiny and static (1-2 typically)
        mask_j = jax.nn.one_hot(top_i[:, j], E, dtype=jnp.int32)  # [T, E]
        pos_j = jnp.cumsum(mask_j, axis=0) - 1 + prev_count[None, :]  # [T, E]
        prev_count = prev_count + jnp.sum(mask_j, axis=0)
        keep = (pos_j < capacity) & (mask_j > 0)  # [T, E]
        slot = jax.nn.one_hot(jnp.clip(pos_j, 0, capacity - 1), capacity)  # [T,E,C]
        sel = jnp.where(keep[..., None], slot, 0.0)
        dispatch = dispatch + sel
        combine = combine + sel * top_g[:, j][:, None, None]
    return dispatch, combine


def moe_ffn(
    cfg: ModelConfig, lp: dict[str, Any], x: jnp.ndarray, capacity: int | None = None
) -> jnp.ndarray:
    """Sparse FFN over flattened tokens x: [T, D] → [T, D].

    lp holds this layer's "router" [D, E], "w1e"/"w3e" [E, D, F],
    "w2e" [E, F, D] (sliced from the stacked [L, ...] tree by the caller's
    scan). With `P("ep")` on the E dim, GSPMD inserts the token all-to-all
    around the batched expert matmuls.

    `capacity=T` makes the layer dropless — decode passes this (a [B, E, B]
    dispatch over engine slots is tiny, and dropping tokens at decode time
    would silently degrade generations); prefill uses the capacity factor to
    bound the batched expert matmul at large T.
    """
    T, D = x.shape
    C = capacity if capacity is not None else expert_capacity(cfg, T)
    logits = jnp.einsum("td,de->te", x, lp["router"])  # router in f32 below
    dispatch, combine = moe_dispatch(cfg, logits, C)

    xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E, C, D]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, lp["w1e"]))
    up = jnp.einsum("ecd,edf->ecf", xe, lp["w3e"])
    ye = jnp.einsum("ecf,efd->ecd", gate * up, lp["w2e"])  # [E, C, D]
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), ye)  # [T, D]
    return y


def init_moe_layer_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype
) -> dict[str, jnp.ndarray]:
    """Stacked [L, ...] MoE weights for every layer (Mixtral-style all-MoE)."""
    L, D, E, F = cfg.n_layers, cfg.dim, cfg.n_experts, cfg.ffn_hidden
    keys = jax.random.split(key, 4)

    def w(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)
        ).astype(dtype)

    return {
        "router": w(keys[0], (L, D, E), D),
        "w1e": w(keys[1], (L, E, D, F), D),
        "w3e": w(keys[2], (L, E, D, F), D),
        "w2e": w(keys[3], (L, E, F, D), F),
    }
