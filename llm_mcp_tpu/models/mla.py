"""Multi-head Latent Attention (MLA) — DeepSeek-V2/V3-style KV compression,
TPU-first.

Why it exists here: decode is cache-bandwidth-bound (see
kernels/attention.py), and long-context serving is capped by KV bytes per
token. GQA at 8B-class shapes stores 2 * n_kv_heads * head_dim = 2048
values/token/layer; MLA stores ONE shared latent (kv_lora_rank) plus a
shared rope key (qk_rope_head_dim) — 576 values/token/layer at DeepSeek
proportions, ~3.6x more context per HBM byte, with per-head K/V
re-expanded from the latent by weight matrices that live in HBM once.

TPU-first choices:
  - **Decode runs ABSORBED**: queries fold through the k-up-projection
    (q̃ = q_nope @ W_uk per head) so attention works directly against the
    latent cache — two dense einsums on the MXU, no per-head K/V ever
    materialized at decode time. The value side re-expands only the
    attended context vector (H x kv_lora_rank @ kv_lora_rank x v_dim).
  - **Prefill runs EXPANDED**: at prompt lengths the O(S) per-head K/V is
    cheap relative to the weight pass, and the expanded form is one
    standard masked attention XLA fuses well.
  - **Engine compatibility by shape**: the latent cache poses as a
    one-kv-head llama cache — k-cache := latents [L, B, 1, S, kv_lora_rank],
    v-cache := rope keys [L, B, 1, S, qk_rope_head_dim] — so the engine's
    entire slot machinery (bucketed inserts, chunk writes, compaction
    scatter, donation, recovery) works unchanged. `llama_prefill` /
    `llama_decode_step` dispatch here when cfg.kv_lora_rank > 0.

Reference parity note: the reference serves deepseek-architecture models
only through Ollama (`discovery.go:510` infers metadata from the name);
this module is what "serving a deepseek-class architecture in-process"
means TPU-side. Rope here is the repo's split-half convention; loading
published DeepSeek checkpoints additionally needs their yarn-scaled rope
and shared-expert MoE (tracked in NOTES_r03.md), so the in-repo configs
are the `tiny-mla` test config and an `mla-8b` long-context serving
config with llama-8B-scale proportions.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..kernels.attention import paged_gather, ragged_prefill_attend_mla
from ..ops.norms import rms_norm as _rms_norm
from ..ops.rope import apply_rope, rope_tables
from .configs import ModelConfig
from .quant import qdot, scan_unroll

# llama.py imports this module only lazily inside its dispatch functions, so
# pulling the shared decoder helpers in at module level is cycle-free
from .llama import (
    _embed_in,
    _ffn_residual,
    _logits,
    _norm,
    quantize_kv,
)

Params = Any


def _dims(cfg: ModelConfig) -> tuple[int, int, int, int]:
    """(n_heads, qk_nope, qk_rope, v_dim)."""
    return cfg.n_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim


def mla_scale(cfg: ModelConfig) -> float:
    # yarn_attn_mscale folds DeepSeek-V2's yarn magnitude correction
    # ((0.1·mscale_all_dim·ln(factor)+1)²) into the softmax scale
    return (
        cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
    ) ** -0.5 * cfg.yarn_attn_mscale


def _mla_attn_weights(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype, L: int
) -> Params:
    """Stacked [L, ...] MLA attention weights (dense-q factorization)."""
    H, dn, dr, dv = _dims(cfg)
    D, R = cfg.dim, cfg.kv_lora_rank

    def w(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)
        ).astype(dtype)

    kq = jax.random.split(key, 4)
    return {
        "wq_mla": w(kq[0], (L, D, H * (dn + dr)), D),
        # one matmul produces (latent c_kv | shared rope key), HF
        # kv_a_proj_with_mqa layout
        "w_dkv": w(kq[1], (L, D, R + dr), D),
        "kv_norm": jnp.ones((L, R), dtype=dtype),  # kv_a_layernorm
        # up-projection from the latent to per-head (k_nope | v)
        "w_ukv": w(kq[2], (L, R, H * (dn + dv)), R),
        "wo_mla": w(kq[3], (L, H * dv, D), H * dv),
    }


def init_mla_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random-init MLA decoder weights (dense-q variant: q_lora_rank == 0
    projects queries directly, as DeepSeek-V2-Lite does).

    With cfg.first_dense_layers > 0 (DeepSeek-V2 MoE), the layer stack
    splits into params["dense_layers"] (layers 0..k-1, dense FFN at
    ffn_hidden) and params["layers"] (the MoE stack) — two uniform scans
    instead of one, since the FFN weight shapes differ."""
    import dataclasses

    from .llama import init_llama_params  # local: dispatch entry point

    if cfg.q_lora_rank:
        raise ValueError(
            "q_lora_rank > 0 (low-rank query path) is not implemented; use "
            "the dense-q MLA variant (q_lora_rank=0, V2-Lite style)"
        )
    k_dense = cfg.first_dense_layers if cfg.n_experts else 0
    L_main = cfg.n_layers - k_dense
    # the base init skips wq/wk/wv/wo for MLA configs (they would be
    # built at full GQA size only to be discarded — a ~4 GB transient at
    # 8B-class shapes)
    cfg_main = (
        dataclasses.replace(cfg, n_layers=L_main) if k_dense else cfg
    )
    base = init_llama_params(cfg_main, key, dtype=dtype, _dispatch=False)
    layers = base["layers"]
    for k in ("wq", "wk", "wv", "wo", "bq", "bk", "bv"):
        layers.pop(k, None)
    layers.update(_mla_attn_weights(cfg, jax.random.fold_in(key, 7), dtype, L_main))
    if k_dense:
        cfg_dense = dataclasses.replace(cfg, n_layers=k_dense, n_experts=0)
        dense = init_llama_params(
            cfg_dense, jax.random.fold_in(key, 11), dtype=dtype, _dispatch=False
        )["layers"]
        for k in ("wq", "wk", "wv", "wo", "bq", "bk", "bv"):
            dense.pop(k, None)
        dense.update(
            _mla_attn_weights(cfg, jax.random.fold_in(key, 13), dtype, k_dense)
        )
        base["dense_layers"] = dense
    return base


def init_mla_cache(
    cfg: ModelConfig,
    batch: int,
    max_seq: int,
    dtype: jnp.dtype = jnp.bfloat16,
    quantized: bool = False,
) -> dict[str, Any]:
    """Latent cache in the engine's (k, v) pair convention:
    k := latents [L, B, 1, S, kv_lora_rank], v := rope keys
    [L, B, 1, S, qk_rope_head_dim]. The fake one-head axis keeps every
    slot-machinery code path (inserts, chunked writes, compaction)
    byte-compatible with the llama cache layout.

    `quantized=True` stores int8 payloads with per-token scales (the same
    post-dot scale-folding scheme as the GQA int8 cache): MLA's latent is
    already ~3.6x smaller than GQA K/V by VALUE COUNT; int8 makes it
    ~7x smaller by BYTES — double the context per HBM byte again."""
    L, R, dr = cfg.n_layers, cfg.kv_lora_rank, cfg.qk_rope_head_dim
    if quantized:
        return {
            "k": {
                "q": jnp.zeros((L, batch, 1, max_seq, R), dtype=jnp.int8),
                "s": jnp.zeros((L, batch, 1, max_seq), dtype=dtype),
            },
            "v": {
                "q": jnp.zeros((L, batch, 1, max_seq, dr), dtype=jnp.int8),
                "s": jnp.zeros((L, batch, 1, max_seq), dtype=dtype),
            },
        }
    return {
        "k": jnp.zeros((L, batch, 1, max_seq, R), dtype=dtype),
        "v": jnp.zeros((L, batch, 1, max_seq, dr), dtype=dtype),
    }


def _latents(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    """x [..., D] → (c_kv [..., R] normed, k_rope [..., dr] pre-rope)."""
    R = cfg.kv_lora_rank
    ckr = qdot(x, lp["w_dkv"])  # [..., R + dr]
    c = _rms_norm(ckr[..., :R], lp["kv_norm"], cfg.norm_eps)
    return c, ckr[..., R:]


def _queries(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    """x [..., D] → (q_nope [..., H, dn], q_rope [..., H, dr])."""
    H, dn, dr, _ = _dims(cfg)
    q = qdot(x, lp["wq_mla"]).reshape(*x.shape[:-1], H, dn + dr)
    return q[..., :dn], q[..., dn:]


def mla_prefill(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32 right-padded prompts
    lengths: jnp.ndarray,  # [B] int32 true lengths
    quant_kv: bool = False,  # int8 latents (per-token scales) inside the scan
) -> tuple[jnp.ndarray, Any, Any]:
    """Causal prefill with QUERY-BLOCKED expanded attention: per-head K/V
    re-materialize once (O(S) memory), but scores/probs only ever exist for
    one query block at a time — [B, H, QB, S] instead of [B, H, S, S].
    A naive expanded form would build an 8.6 GB f32 score tensor per layer
    at S=8192/H=32; blocking keeps long-context prefill linear in S (the
    same job chunked prefill does for the llama families).

    Returns (last_logits [B, V] f32, latents [L, B, 1, S, R], rope_keys
    [L, B, 1, S, dr]) — the cache rows to insert at the request's slot
    (post-rope, decode-ready)."""
    H, dn, dr, dv = _dims(cfg)
    B, S = tokens.shape
    scale = mla_scale(cfg)
    h = _embed_in(cfg, params, tokens)  # [B, S, D]
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]
    cos, sin = rope_tables(cfg, dr, positions)  # [1, S, dr/2]
    key_pos = jnp.arange(S, dtype=jnp.int32)
    valid_k = key_pos[None, :] < lengths[:, None]  # [B, S]
    neg = jnp.float32(-1e30)
    QB = next((c for c in (256, 128, 64, 32, 16, 8, 4, 2, 1) if S % c == 0))
    nb = S // QB

    def layer(h, lp):
        x = _norm(cfg, h, lp["attn_norm"])
        qn, qr = _queries(cfg, lp, x)  # [B, S, H, dn/dr]
        qr = apply_rope(qr, cos, sin)
        c, kr = _latents(cfg, lp, x)  # [B, S, R], [B, S, dr]
        kr = apply_rope(kr[..., None, :], cos, sin)[..., 0, :]  # shared key
        kv = qdot(c, lp["w_ukv"]).reshape(B, S, H, dn + dv)
        kn, v = kv[..., :dn], kv[..., dn:]

        # query blocks ride a scan: [nb, B, QB, H, d] xs against the full
        # (linear-size) keys closed over — one block's [B, H, QB, S] scores
        # live at a time
        qn_b = qn.reshape(B, nb, QB, H, dn).transpose(1, 0, 2, 3, 4)
        qr_b = qr.reshape(B, nb, QB, H, dr).transpose(1, 0, 2, 3, 4)
        pos_b = jnp.arange(S, dtype=jnp.int32).reshape(nb, QB)

        def qblock(_, xs):
            qnj, qrj, posj = xs  # [B, QB, H, ·], [QB]
            scores = (
                jnp.einsum("bqhd,bkhd->bhqk", qnj, kn)
                + jnp.einsum("bqhd,bkd->bhqk", qrj, kr)
            ).astype(jnp.float32) * scale
            mask = (key_pos[None, :] <= posj[:, None])[None, None] & valid_k[
                :, None, None, :
            ]  # [B, 1|QB, S] → [B, 1, QB, S]
            scores = jnp.where(mask, scores, neg)
            probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)  # [B, QB, H, dv]
            return None, ctx

        _, ctx_b = jax.lax.scan(qblock, None, (qn_b, qr_b, pos_b))
        ctx = ctx_b.transpose(1, 0, 2, 3, 4).reshape(B, S, H * dv)
        h = h + qdot(ctx, lp["wo_mla"])
        h = _ffn_residual(cfg, lp, h, moe_valid=valid_k)
        if quant_kv:
            # quantize INSIDE the scan: the stacked bf16 latents of a long
            # admission never materialize (llama_prefill's same trick)
            return h, (quantize_kv(c), quantize_kv(kr))
        return h, (c, kr)

    def scan_layer(carry, lp):
        h = carry
        h, (c, kr) = layer(h, lp)
        return h, (c, kr)

    if "dense_layers" in params:
        # DeepSeek first-dense prologue (layers 0..k-1): same layer fn, the
        # FFN shape difference lives in the params (see _ffn_residual)
        h, (cs_d, krs_d) = jax.lax.scan(scan_layer, h, params["dense_layers"])
    h, (cs, krs) = jax.lax.scan(scan_layer, h, params["layers"])
    if "dense_layers" in params:
        cs = jax.tree.map(lambda a, b: jnp.concatenate([a, b], axis=0), cs_d, cs)
        krs = jax.tree.map(lambda a, b: jnp.concatenate([a, b], axis=0), krs_d, krs)
    last = jnp.clip(lengths - 1, 0, S - 1)
    h_last = jnp.take_along_axis(h, last[:, None, None], axis=1)[:, 0]
    logits = _logits(cfg, params, h_last)

    def to_engine_layout(x):
        # [L, B, S, ·] → engine layout [L, B, 1, S, ·]
        if isinstance(x, dict):
            return {"q": x["q"][:, :, None], "s": x["s"][:, :, None]}
        return x[:, :, None]

    return logits, to_engine_layout(cs), to_engine_layout(krs)


def mla_prefill_chunk_batch(
    cfg: ModelConfig,
    params: Params,
    cache_c: Any,  # [L, B, 1, S, R] latents (or int8 {"q","s"} pytree)
    cache_r: Any,  # [L, B, 1, S, dr] rope keys
    tokens: jnp.ndarray,  # [A, C] int32 — right-padded chunks, one per slot
    slots: jnp.ndarray,  # [A] int32 engine slots
    starts: jnp.ndarray,  # [A] int32 absolute position of each chunk's start
    nvalid: jnp.ndarray,  # [A] int32 valid tokens per chunk
    skey: int = 0,  # STATIC bound on the PAST key range (0 = whole S)
    all_logits: bool = False,  # STATIC: logits at every chunk position
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, Any, Any]:
    """Batched chunked prefill for MLA — the absorbed-attention analog of
    `llama_prefill_chunk_batch` (same engine contract: one bounded chunk for
    up to A slots in a single dispatch, read-past-then-write-in-place,
    static (C, skey) buckets).

    The chunk's queries fold through W_uk exactly as `mla_decode_step` does,
    so the PAST segment scores straight against the latent cache — context
    prefilled by earlier chunks is never re-expanded to per-head K/V. The
    SELF segment scores against the chunk's own in-register latents (exact
    bf16 even over an int8 cache — the decode kernel's current-token
    override, generalized to C tokens). One joint softmax over [past |
    self]; the value side re-expands only the attended [H, R] context
    through W_uv. This is what unlocks the engine's prompt-prefix KV cache
    for the MLA family: a prefix hit copies latent rows, and the suffix
    rides this path with start = P0.
    """
    H, dn, dr, dv = _dims(cfg)
    quantized = isinstance(cache_c, dict)
    L, B, _, S, R = (cache_c["q"] if quantized else cache_c).shape
    A, C = tokens.shape
    Sk = min(skey, S) if skey else S
    scale = mla_scale(cfg)
    neg = jnp.float32(-1e30)
    slots = jnp.asarray(slots, dtype=jnp.int32)
    starts = jnp.asarray(starts, dtype=jnp.int32)
    nvalid = jnp.asarray(nvalid, dtype=jnp.int32)

    h = _embed_in(cfg, params, tokens)  # [A, C, D]
    q_pos = starts[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]  # [A, C]
    cos, sin = rope_tables(cfg, dr, q_pos)  # [A, C, dr/2]
    key_pos = jnp.arange(Sk, dtype=jnp.int32)
    # past segment: cache rows strictly before each chunk's start
    past_mask = jnp.broadcast_to(
        key_pos[None, None, :] < starts[:, None, None], (A, C, Sk)
    )
    # self segment: causal within the chunk (pad rows past nvalid are
    # written but never attended by valid queries — llama chunk invariant)
    c_idx = jnp.arange(C, dtype=jnp.int32)
    self_mask = jnp.broadcast_to((c_idx[None, :] <= c_idx[:, None])[None], (A, C, C))

    # Block-indirect past reads through each slot's table (shared prefix
    # latents resolve to pool rows); only the blocks covering the static
    # skey bucket are gathered. Writes stay contiguous — chunk positions
    # are private blocks, which live at their identity homes.
    ptbl = None
    if paged is not None:
        nbs_full = paged["tbl"].shape[1]
        bt = S // nbs_full
        nsel = max(1, -(-Sk // bt))
        ptbl = jnp.take(paged["tbl"], slots, axis=0)[:, :nsel]

    def layer(carry, lp):
        h, cc_all, cr_all, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        qn, qr = _queries(cfg, lp, x)  # [A, C, H, dn/dr]
        qr = apply_rope(qr, cos, sin)
        c, kr = _latents(cfg, lp, x)  # [A, C, R], [A, C, dr]
        kr = apply_rope(kr[..., None, :], cos, sin)[..., 0, :]
        w_uk, w_uv = _absorbed_w(lp, h.dtype, R, H, dn, dv)
        qt = jnp.einsum("achd,rhd->achr", qn, w_uk)  # [A, C, H, R]

        # ---- reads first: past latents/rope keys from the PRE-write cache
        def past_rows(cache, d, pool=None):
            if ptbl is not None:
                return paged_gather(
                    jax.lax.dynamic_index_in_dim(cache, li, 0, keepdims=False),
                    jax.lax.dynamic_index_in_dim(pool, li, 0, keepdims=False),
                    ptbl, nbs=nbs_full,
                )[:, 0, :Sk]  # [A, Sk, d] (d absent for scale planes)
            return jnp.stack(
                [
                    jax.lax.dynamic_slice(
                        cache, (li, slots[a], 0, 0, 0), (1, 1, 1, Sk, d)
                    )[0, 0, 0]
                    for a in range(A)
                ]
            )  # [A, Sk, d]

        def past_scales(cache_s, pool_s=None):
            if ptbl is not None:
                return past_rows(cache_s, 0, pool_s).astype(jnp.float32)
            return jnp.stack(
                [
                    jax.lax.dynamic_slice(
                        cache_s, (li, slots[a], 0, 0), (1, 1, 1, Sk)
                    )[0, 0, 0]
                    for a in range(A)
                ]
            ).astype(jnp.float32)  # [A, Sk]

        pk = None if paged is None else paged["k"]
        pv = None if paged is None else paged["v"]
        if quantized:
            lat = past_rows(cc_all["q"], R, pk and pk["q"])
            rop = past_rows(cr_all["q"], dr, pv and pv["q"])
            ls = past_scales(cc_all["s"], pk and pk["s"])
            rs = past_scales(cr_all["s"], pv and pv["s"])
            # per-token dequant scales fold POST-DOT (decode path's trick)
            s_past = (
                jnp.einsum("achr,asr->ahcs", qt, lat.astype(qt.dtype)).astype(
                    jnp.float32
                )
                * ls[:, None, None, :]
                + jnp.einsum("achd,asd->ahcs", qr, rop.astype(qr.dtype)).astype(
                    jnp.float32
                )
                * rs[:, None, None, :]
            ) * scale
        else:
            lat = past_rows(cc_all, R, pk)
            rop = past_rows(cr_all, dr, pv)
            s_past = (
                jnp.einsum("achr,asr->ahcs", qt, lat.astype(qt.dtype))
                + jnp.einsum("achd,asd->ahcs", qr, rop.astype(qr.dtype))
            ).astype(jnp.float32) * scale
        s_self = (
            jnp.einsum("achr,atr->ahct", qt, c)
            + jnp.einsum("achd,atd->ahct", qr, kr)
        ).astype(jnp.float32) * scale
        s_past = jnp.where(past_mask[:, None], s_past, neg)
        s_self = jnp.where(self_mask[:, None], s_self, neg)

        # joint softmax over [past | self]
        s = jnp.concatenate([s_past, s_self], axis=-1)  # [A, H, C, Sk+C]
        probs = jax.nn.softmax(s, axis=-1)
        p_past, p_self = probs[..., :Sk], probs[..., Sk:]
        if quantized:
            p_past = p_past * ls[:, None, None, :]  # value-side dequant
        ctx_lat = jnp.einsum(
            "ahcs,asr->achr", p_past.astype(h.dtype), lat.astype(h.dtype)
        ) + jnp.einsum("ahct,atr->achr", p_self.astype(h.dtype), c)
        ctx = jnp.einsum("achr,rhd->achd", ctx_lat, w_uv).reshape(A, C, H * dv)
        h = h + qdot(ctx, lp["wo_mla"])
        h = _ffn_residual(cfg, lp, h, moe_valid=c_idx[None, :] < nvalid[:, None])

        # ---- writes last: in place (write-after-read)
        if quantized:
            cq = quantize_kv(c, scale_dtype=cc_all["s"].dtype)
            rq = quantize_kv(kr, scale_dtype=cr_all["s"].dtype)
            for a in range(A):
                cc_all = {
                    "q": jax.lax.dynamic_update_slice(
                        cc_all["q"], cq["q"][a][None, None, None],
                        (li, slots[a], 0, starts[a], 0),
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        cc_all["s"], cq["s"][a][None, None, None],
                        (li, slots[a], 0, starts[a]),
                    ),
                }
                cr_all = {
                    "q": jax.lax.dynamic_update_slice(
                        cr_all["q"], rq["q"][a][None, None, None],
                        (li, slots[a], 0, starts[a], 0),
                    ),
                    "s": jax.lax.dynamic_update_slice(
                        cr_all["s"], rq["s"][a][None, None, None],
                        (li, slots[a], 0, starts[a]),
                    ),
                }
        else:
            for a in range(A):
                cc_all = jax.lax.dynamic_update_slice(
                    cc_all, c[a][None, None, None].astype(cc_all.dtype),
                    (li, slots[a], 0, starts[a], 0),
                )
                cr_all = jax.lax.dynamic_update_slice(
                    cr_all, kr[a][None, None, None].astype(cr_all.dtype),
                    (li, slots[a], 0, starts[a], 0),
                )
        return (h, cc_all, cr_all, li + 1), None

    carry = (h, cache_c, cache_r, jnp.int32(0))
    if "dense_layers" in params:
        # DeepSeek first-dense prologue; carried li keeps cache rows aligned
        # with absolute layer position
        carry, _ = jax.lax.scan(layer, carry, params["dense_layers"])
    (h, new_c, new_r, _), _ = jax.lax.scan(layer, carry, params["layers"])
    if all_logits:
        return _logits(cfg, params, h), new_c, new_r  # [A, C, V]
    last = jnp.take_along_axis(
        h, jnp.clip(nvalid - 1, 0, C - 1)[:, None, None], axis=1
    )[:, 0]  # [A, D]
    return _logits(cfg, params, last), new_c, new_r


def mla_prefill_chunk_ragged(
    cfg: ModelConfig,
    params: Params,
    cache_c: Any,  # [L, B, 1, S, R] latents (or int8 {"q","s"} pytree)
    cache_r: Any,  # [L, B, 1, S, dr] rope keys
    tokens: jnp.ndarray,  # [T] int32 — PACKED chunks, rows back-to-back
    rowids: jnp.ndarray,  # [T] int32 — descriptor row per token, sorted
    #   ascending; pads carry rowid == Rn
    positions: jnp.ndarray,  # [T] int32 — absolute positions; pads carry S
    slots: jnp.ndarray,  # [Rn] int32
    starts: jnp.ndarray,  # [Rn] int32 cached-prefix length per row
    last_idx: jnp.ndarray,  # [Rn] int32 packed index of each row's last token
    skey: int = 0,  # STATIC past bound for the XLA arm (kernel arm ignores)
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, Any, Any]:
    """Ragged chunked prefill for MLA — the packed-descriptor twin of
    `mla_prefill_chunk_batch` (see `llama_prefill_chunk_ragged` for the
    descriptor contract). Queries fold through W_uk so the cached prefix
    scores straight against latent rows, streamed block-indirect by
    `kernels/attention.py:ragged_prefill_attend_mla`; the chunk's own
    latents/rope keys stay exact bf16 from registers; the value side
    re-expands only the attended [H, R] context through W_uv.

    Returns (logits [Rn, V] f32 at each row's `last_idx` token, new_c, new_r).
    """
    H, dn, dr, dv = _dims(cfg)
    quantized = isinstance(cache_c, dict)
    L, B, _, S, R = (cache_c["q"] if quantized else cache_c).shape
    T = tokens.shape[0]
    Rn = slots.shape[0]
    scale = mla_scale(cfg)
    slots = jnp.asarray(slots, dtype=jnp.int32)
    starts = jnp.asarray(starts, dtype=jnp.int32)
    rowids = jnp.asarray(rowids, dtype=jnp.int32)
    positions = jnp.asarray(positions, dtype=jnp.int32)
    offsets = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            jnp.sum(
                (rowids[None, :] < jnp.arange(1, Rn + 1, dtype=jnp.int32)[:, None]),
                axis=1,
                dtype=jnp.int32,
            ),
        ]
    )  # [Rn+1]
    wslot = slots[jnp.clip(rowids, 0, Rn - 1)]  # [T]
    moe_valid = rowids < Rn
    btbl = paged["tbl"] if paged is not None else None
    pool_c = paged["k"] if paged is not None else None
    pool_r = paged["v"] if paged is not None else None

    h = _embed_in(cfg, params, tokens)  # [T, D]
    cos, sin = rope_tables(cfg, dr, positions)  # [T, dr/2]

    def layer(carry, lp):
        h, cc_all, cr_all, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        qn, qr = _queries(cfg, lp, x)  # [T, H, dn/dr]
        qr = apply_rope(qr, cos, sin)
        c, kr = _latents(cfg, lp, x)  # [T, R], [T, dr]
        kr = apply_rope(kr[..., None, :], cos, sin)[..., 0, :]
        w_uk, w_uv = _absorbed_w(lp, h.dtype, R, H, dn, dv)
        qt = jnp.einsum("thd,rhd->thr", qn, w_uk)  # [T, H, R]

        # ---- reads first: ragged attention over [cached past | packed self]
        ctx_lat = ragged_prefill_attend_mla(
            qt, qr, c, kr, cc_all, cr_all, li, rowids, offsets, slots, starts,
            scale=scale, skey=skey, block_tables=btbl,
            pool_c=pool_c, pool_r=pool_r,
        )  # [T, H, R]
        ctx = jnp.einsum("thr,rhd->thd", ctx_lat, w_uv).reshape(T, H * dv)
        h = h + qdot(ctx, lp["wo_mla"])
        h = _ffn_residual(cfg, lp, h, moe_valid=moe_valid)

        # ---- writes last: positional scatter, pads (position S) DROP ----
        if quantized:
            cq = quantize_kv(c, scale_dtype=cc_all["s"].dtype)
            rq = quantize_kv(kr, scale_dtype=cr_all["s"].dtype)
            cc_all = {
                "q": cc_all["q"].at[li, wslot, 0, positions].set(
                    cq["q"], mode="drop"
                ),
                "s": cc_all["s"].at[li, wslot, 0, positions].set(
                    cq["s"], mode="drop"
                ),
            }
            cr_all = {
                "q": cr_all["q"].at[li, wslot, 0, positions].set(
                    rq["q"], mode="drop"
                ),
                "s": cr_all["s"].at[li, wslot, 0, positions].set(
                    rq["s"], mode="drop"
                ),
            }
        else:
            cc_all = cc_all.at[li, wslot, 0, positions].set(
                c.astype(cc_all.dtype), mode="drop"
            )
            cr_all = cr_all.at[li, wslot, 0, positions].set(
                kr.astype(cr_all.dtype), mode="drop"
            )
        return (h, cc_all, cr_all, li + 1), None

    carry = (h, cache_c, cache_r, jnp.int32(0))
    if "dense_layers" in params:
        carry, _ = jax.lax.scan(layer, carry, params["dense_layers"])
    (h, new_c, new_r, _), _ = jax.lax.scan(layer, carry, params["layers"])
    last = jnp.take(h, jnp.clip(last_idx, 0, T - 1), axis=0)  # [Rn, D]
    return _logits(cfg, params, last), new_c, new_r


def _absorbed_w(lp, h_dtype, R, H, dn, dv):
    """(W_uk [R,H,dn], W_uv [R,H,dv]) from this layer's (possibly int8)
    up-projection — dequantized once per step."""
    w_ukv = lp["w_ukv"]
    if isinstance(w_ukv, dict):
        w_ukv = w_ukv["q"].astype(h_dtype) * w_ukv["s"].astype(h_dtype)
    w_ukv = w_ukv.reshape(R, H, dn + dv)
    return w_ukv[:, :, :dn], w_ukv[:, :, dn:]


def mla_decode_step(
    cfg: ModelConfig,
    params: Params,
    cache_c: jnp.ndarray,  # [L, B, 1, S, R] latents (engine "k")
    cache_r: jnp.ndarray,  # [L, B, 1, S, dr] rope keys (engine "v")
    tokens: jnp.ndarray,  # [Ba] int32
    lengths: jnp.ndarray,  # [Ba] int32 — write position per row
    slot_ids: jnp.ndarray | None = None,  # [Ba] compaction indirection
    attn_impl: str = "xla",
    paged: dict | None = None,  # {"tbl","k","v"} physical paging operand
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One absorbed-attention decode step for all slots.

    Attention runs IN LATENT SPACE: q̃[h] = q_nope[h] @ W_uk[:, h] gives
    per-head queries against the shared latents; the value side re-expands
    only the attended [H, R] context. The caches follow the llama xla-path
    structure (scan carry, in-place scatter at `lengths`, OOB rows
    dropped → parked-slot invariant preserved).

    With an int8 latent cache and attn_impl="pallas", attention runs the
    s8-MXU kernel (kernels/attention.py:decode_attend_q8_mla) against the
    PRE-append cache (the kernel overrides position w with the exact
    vectors), and the appends defer to ONE batched scatter per cache after
    the layer scan — instead of L per-layer scatters, each of which XLA
    turns into a full-cache copy."""
    H, dn, dr, dv = _dims(cfg)
    quantized = isinstance(cache_c, dict)
    L, B, _, S, R = (cache_c["q"] if quantized else cache_c).shape
    Ba = tokens.shape[0]
    scale = mla_scale(cfg)
    h = _embed_in(cfg, params, tokens)  # [Ba, D]
    cos, sin = rope_tables(cfg, dr, lengths)  # [Ba, dr/2]

    rows = jnp.arange(B, dtype=jnp.int32) if slot_ids is None else slot_ids
    b_idx = rows[:, None]  # [Ba, 1] scatter rows
    w_idx = lengths[:, None]  # [Ba, 1] — broadcast to [Ba, 1(head)]
    key_pos = jnp.arange(S)[None, :]
    attn_mask = key_pos <= lengths[:, None]  # [Ba, S]
    neg = jnp.float32(-1e30)

    def rowsel(x):
        return x if slot_ids is None else jnp.take(x, slot_ids, axis=0)

    ptbl = None if paged is None else jnp.take(paged["tbl"], rows, axis=0)

    def layer(carry, lp):
        h, cc_all, cr_all, li = carry
        x = _norm(cfg, h, lp["attn_norm"])
        qn, qr = _queries(cfg, lp, x)  # [Ba, H, dn/dr]
        qr = apply_rope(qr, cos, sin)
        c, kr = _latents(cfg, lp, x)  # [Ba, R], [Ba, dr]
        kr = apply_rope(kr[:, None], cos, sin)[:, 0]
        # scatter this step's latent/rope-key at (layer, row, 0, position) —
        # in place on the scan-carried donated buffers (the llama xla-path
        # pattern: per-layer one-token scatters, never a full-cache copy);
        # OOB (parked) rows dropped
        zero = jnp.zeros_like(b_idx)
        if quantized:
            cq, krq = quantize_kv(c), quantize_kv(kr)
            cc_all = {
                "q": cc_all["q"].at[li, b_idx, zero, w_idx].set(cq["q"][:, None]),
                "s": cc_all["s"].at[li, b_idx, zero, w_idx].set(
                    cq["s"][:, None].astype(cc_all["s"].dtype)
                ),
            }
            cr_all = {
                "q": cr_all["q"].at[li, b_idx, zero, w_idx].set(krq["q"][:, None]),
                "s": cr_all["s"].at[li, b_idx, zero, w_idx].set(
                    krq["s"][:, None].astype(cr_all["s"].dtype)
                ),
            }
        else:
            cc_all = cc_all.at[li, b_idx, zero, w_idx].set(
                c[:, None].astype(cc_all.dtype)
            )
            cr_all = cr_all.at[li, b_idx, zero, w_idx].set(
                kr[:, None].astype(cr_all.dtype)
            )
        # absorbed queries: q̃[h] = q_nope[h] @ W_uk[:, h]  → [Ba, H, R]
        w_uk, w_uv = _absorbed_w(lp, h.dtype, R, H, dn, dv)
        qt = jnp.einsum("bhd,rhd->bhr", qn, w_uk)

        def sel(x, pool=None):
            xl = jax.lax.dynamic_index_in_dim(x, li, 0, keepdims=False)
            if ptbl is None:
                return rowsel(xl[:, 0])
            pp = jax.lax.dynamic_index_in_dim(pool, li, 0, keepdims=False)
            return paged_gather(xl, pp, ptbl)[:, 0]

        pk = None if paged is None else paged["k"]
        pv = None if paged is None else paged["v"]
        if quantized:
            lat = sel(cc_all["q"], pk and pk["q"])  # [Ba, S, R] int8 payload
            rop = sel(cr_all["q"], pv and pv["q"])  # [Ba, S, dr] int8
            ls = sel(cc_all["s"], pk and pk["s"]).astype(jnp.float32)  # [Ba, S]
            rs = sel(cr_all["s"], pv and pv["s"]).astype(jnp.float32)
            # per-token dequant scales fold POST-DOT (the GQA int8 cache's
            # trick): each dot's scores multiply by its own scale row, and
            # the value-side scale folds into the probs before the PV dot
            s_nope = jnp.einsum("bhr,bsr->bhs", qt, lat.astype(qt.dtype)).astype(
                jnp.float32
            ) * ls[:, None, :]
            s_rope = jnp.einsum("bhd,bsd->bhs", qr, rop.astype(qr.dtype)).astype(
                jnp.float32
            ) * rs[:, None, :]
            scores = (s_nope + s_rope) * scale
            scores = jnp.where(attn_mask[:, None, :], scores, neg)
            probs = jax.nn.softmax(scores, axis=-1)
            pl = (probs * ls[:, None, :]).astype(h.dtype)
            ctx_lat = jnp.einsum("bhs,bsr->bhr", pl, lat.astype(h.dtype))
        else:
            lat = sel(cc_all, pk)  # [Ba, S, R]
            rop = sel(cr_all, pv)  # [Ba, S, dr]
            scores = (
                jnp.einsum("bhr,bsr->bhs", qt, lat.astype(qt.dtype))
                + jnp.einsum("bhd,bsd->bhs", qr, rop.astype(qr.dtype))
            ).astype(jnp.float32) * scale
            scores = jnp.where(attn_mask[:, None, :], scores, neg)
            probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
            ctx_lat = jnp.einsum("bhs,bsr->bhr", probs, lat.astype(probs.dtype))
        ctx = jnp.einsum("bhr,rhd->bhd", ctx_lat, w_uv).reshape(Ba, H * dv)
        h = h + qdot(ctx, lp["wo_mla"])
        h = _ffn_residual(cfg, lp, h, moe_capacity=Ba)  # dropless at decode
        return (h, cc_all, cr_all, li + 1), None

    if quantized and attn_impl == "pallas":
        from ..kernels.attention import decode_attend_q8_mla

        def layer_k(carry, lp):
            h, li = carry
            x = _norm(cfg, h, lp["attn_norm"])
            qn, qr = _queries(cfg, lp, x)
            qr = apply_rope(qr, cos, sin)
            c, kr = _latents(cfg, lp, x)
            kr = apply_rope(kr[:, None], cos, sin)[:, 0]
            w_uk, w_uv = _absorbed_w(lp, h.dtype, R, H, dn, dv)
            qt = jnp.einsum("bhd,rhd->bhr", qn, w_uk)
            ctx_lat = decode_attend_q8_mla(
                qt, qr, c, kr, cache_c, cache_r, li, lengths,
                slot_ids=slot_ids, scale=scale,
                block_tables=None if paged is None else paged["tbl"],
                pool_c=None if paged is None else paged["k"],
                pool_r=None if paged is None else paged["v"],
            )
            ctx = jnp.einsum("bhr,rhd->bhd", ctx_lat.astype(h.dtype), w_uv)
            h = h + qdot(ctx.reshape(Ba, H * dv), lp["wo_mla"])
            h = _ffn_residual(cfg, lp, h, moe_capacity=Ba)
            return (h, li + 1), (c, kr)

        carry = (h, jnp.int32(0))
        cs_d = krs_d = None
        if "dense_layers" in params:
            carry, (cs_d, krs_d) = jax.lax.scan(
                layer_k, carry, params["dense_layers"]
            )
        (h, _), (cs, krs) = jax.lax.scan(
            layer_k, carry, params["layers"], unroll=scan_unroll()
        )
        if cs_d is not None:
            cs = jnp.concatenate([cs_d, cs], axis=0)
            krs = jnp.concatenate([krs_d, krs], axis=0)
        # ONE batched append per cache for all layers (OOB/parked rows drop)
        cq, rq = quantize_kv(cs), quantize_kv(krs)
        l_idx = jnp.arange(L)[:, None]
        bb = rows[None, :]
        ww = lengths[None, :]
        cache_c = {
            "q": cache_c["q"].at[l_idx, bb, 0, ww].set(cq["q"]),
            "s": cache_c["s"].at[l_idx, bb, 0, ww].set(
                cq["s"].astype(cache_c["s"].dtype)
            ),
        }
        cache_r = {
            "q": cache_r["q"].at[l_idx, bb, 0, ww].set(rq["q"]),
            "s": cache_r["s"].at[l_idx, bb, 0, ww].set(
                rq["s"].astype(cache_r["s"].dtype)
            ),
        }
        return _logits(cfg, params, h), cache_c, cache_r

    carry = (h, cache_c, cache_r, jnp.int32(0))
    if "dense_layers" in params:
        # dense prologue first — the carried layer index li keeps the cache
        # rows aligned with absolute layer position
        carry, _ = jax.lax.scan(layer, carry, params["dense_layers"])
    (h, cache_c, cache_r, _), _ = jax.lax.scan(
        layer, carry, params["layers"], unroll=scan_unroll()
    )
    return _logits(cfg, params, h), cache_c, cache_r
