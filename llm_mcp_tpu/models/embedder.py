"""Bidirectional transformer encoder for embeddings, HBM-resident.

Replaces the reference's delegated Ollama `/api/embed` batch path
(`core/internal/api/handlers.go:1942-2015`) and `ollama.embed` jobs
(`worker/llm_worker/main.py:246-261`) with an in-process encoder serving
`POST /v1/embeddings` directly from TPU. Same TPU-first conventions as
models/llama.py: scan over layers, static shapes, bf16 with f32 reductions.

One parameterized encoder serves the BERT families the way one decoder
serves the llama families (the reference trivially serves any embed model
an Ollama host carries, `discovery.go:482-560`):

  - nomic-class (`model_type: nomic_bert`): rope, post-LN LayerNorm,
    gated SwiGLU without linear biases, segment-0 type embeddings
  - classic BERT (`model_type: bert`): learned absolute positions,
    post-LN LayerNorm, ungated GELU MLP, biases everywhere
  - the original TPU-native default: rope + RMSNorm + SwiGLU pre-norm
    (tiny-embed and random-init benchmarks)

Matryoshka `dimensions` truncation (reference `handlers.go:2063-2078` does
client-side truncation as a fallback) is exact here: truncate then
re-normalize — done in the engine so one forward pass serves any requested
dimension.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..ops.norms import rms_norm as _rms_norm
from ..ops.rope import rope_tables, apply_rope
from .configs import ModelConfig
from .quant import embed_lookup, qdot

Params = dict[str, Any]


def _act(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.act == "gelu":
        # erf-based: HF BERT "gelu" is exact, and the tanh approximation
        # drifts embeddings enough to matter for cosine-similarity users
        return jax.nn.gelu(x, approximate=False)
    if cfg.act in ("gelu_new", "gelu_pytorch_tanh"):
        return jax.nn.gelu(x, approximate=True)
    if cfg.act == "relu":
        return jax.nn.relu(x)
    if cfg.act == "silu":
        return jax.nn.silu(x)
    # config inference validates activations; reaching here means a config
    # was hand-built with a name this forward does not implement
    raise ValueError(f"unsupported encoder activation {cfg.act!r}")


def init_embedder_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    hd = cfg.resolved_head_dim
    L, D, H, F, V = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.ffn_hidden, cfg.vocab_size
    keys = jax.random.split(key, 12)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dtype)

    layers: Params = {
        "attn_norm": jnp.ones((L, D), dtype=dtype),
        "wq": w(keys[1], (L, D, H * hd), D),
        "wk": w(keys[2], (L, D, H * hd), D),
        "wv": w(keys[3], (L, D, H * hd), D),
        "wo": w(keys[4], (L, H * hd, D), H * hd),
        "ffn_norm": jnp.ones((L, D), dtype=dtype),
        "w1": w(keys[5], (L, D, F), D),
        "w2": w(keys[7], (L, F, D), F),
    }
    if cfg.enc_gated:
        layers["w3"] = w(keys[6], (L, D, F), D)
    if cfg.enc_norm == "layer":
        layers["attn_norm_b"] = jnp.zeros((L, D), dtype=dtype)
        layers["ffn_norm_b"] = jnp.zeros((L, D), dtype=dtype)
    if cfg.enc_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype=dtype)
        layers["bk"] = jnp.zeros((L, H * hd), dtype=dtype)
        layers["bv"] = jnp.zeros((L, H * hd), dtype=dtype)
        layers["bo"] = jnp.zeros((L, D), dtype=dtype)
        layers["b1"] = jnp.zeros((L, F), dtype=dtype)
        layers["b2"] = jnp.zeros((L, D), dtype=dtype)
        if cfg.enc_gated:
            layers["b3"] = jnp.zeros((L, F), dtype=dtype)

    params: Params = {"embed": w(keys[0], (V, D), D), "layers": layers}
    if cfg.enc_pos == "learned":
        params["pos_embed"] = w(keys[8], (cfg.max_seq_len, D), D)
    if cfg.type_vocab_size:
        params["type_embed"] = w(keys[9], (cfg.type_vocab_size, D), D)
    if cfg.enc_post_ln:
        # post-LN stacks normalize AFTER embeddings and inside each block;
        # there is no final norm
        params["embed_norm"] = jnp.ones((D,), dtype=dtype)
        if cfg.enc_norm == "layer":
            params["embed_norm_b"] = jnp.zeros((D,), dtype=dtype)
    else:
        params["final_norm"] = jnp.ones((D,), dtype=dtype)
    return params


def _norm(cfg: ModelConfig, x: jnp.ndarray, w: jnp.ndarray, b) -> jnp.ndarray:
    if cfg.enc_norm == "layer":
        xf = x.astype(jnp.float32)
        mu = xf.mean(axis=-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
        out = (xf - mu) * jax.lax.rsqrt(var + cfg.norm_eps)
        out = out * w.astype(jnp.float32)
        if b is not None:
            out = out + b.astype(jnp.float32)
        return out.astype(x.dtype)
    return _rms_norm(x, w, cfg.norm_eps)


def embed_forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32 right-padded
    lengths: jnp.ndarray,  # [B] int32
) -> jnp.ndarray:
    """Encode a batch → L2-normalized embeddings [B, D] float32."""
    B, S = tokens.shape
    hd = cfg.resolved_head_dim
    H = cfg.n_heads

    h = embed_lookup(params["embed"], tokens)
    if cfg.enc_pos == "learned":
        h = h + params["pos_embed"][:S][None, :, :].astype(h.dtype)
    if cfg.type_vocab_size:
        h = h + params["type_embed"][0][None, None, :].astype(h.dtype)  # segment 0
    if cfg.enc_post_ln:
        h = _norm(cfg, h, params["embed_norm"], params.get("embed_norm_b"))

    use_rope = cfg.enc_pos == "rope"
    if use_rope:
        positions = jnp.arange(S, dtype=jnp.int32)[None, :]
        cos, sin = rope_tables(cfg, hd, positions)

    valid = jnp.arange(S)[None, :] < lengths[:, None]  # [B, S]
    mask = valid[:, None, :]  # [B, 1(q), S(k)] — bidirectional, pad-masked
    neg = jnp.float32(-1e30)

    def bias(x, lp, k):
        return x + lp[k].astype(x.dtype) if cfg.enc_bias else x

    def attn(x, lp):
        """Attention sublayer; residual/norm order is decided by the caller
        (pre-norm vs post-LN)."""
        q = bias(qdot(x, lp["wq"]), lp, "bq").reshape(B, S, H, hd)
        k = bias(qdot(x, lp["wk"]), lp, "bk").reshape(B, S, H, hd)
        v = bias(qdot(x, lp["wv"]), lp, "bv").reshape(B, S, H, hd)
        if use_rope:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (hd**-0.5)
        scores = jnp.where(mask[:, None, :, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H * hd)
        return bias(qdot(ctx, lp["wo"]), lp, "bo")

    def mlp(x, lp):
        up = bias(qdot(x, lp["w1"]), lp, "b1")
        if cfg.enc_gated:
            up = _act(cfg, up) * bias(qdot(x, lp["w3"]), lp, "b3")
        else:
            up = _act(cfg, up)
        return bias(qdot(up, lp["w2"]), lp, "b2")

    if cfg.enc_post_ln:

        def layer(h, lp):
            h = _norm(cfg, h + attn(h, lp), lp["attn_norm"], lp.get("attn_norm_b"))
            h = _norm(cfg, h + mlp(h, lp), lp["ffn_norm"], lp.get("ffn_norm_b"))
            return h, None

    else:

        def layer(h, lp):
            x = _norm(cfg, h, lp["attn_norm"], lp.get("attn_norm_b"))
            h = h + attn(x, lp)
            x = _norm(cfg, h, lp["ffn_norm"], lp.get("ffn_norm_b"))
            h = h + mlp(x, lp)
            return h, None

    h, _ = jax.lax.scan(layer, h, params["layers"])
    if cfg.enc_post_ln:
        h = h.astype(jnp.float32)
    else:
        h = _norm(cfg, h, params["final_norm"], None).astype(jnp.float32)

    if cfg.pooling == "cls":
        pooled = h[:, 0]
    else:  # masked mean
        w = valid.astype(jnp.float32)[:, :, None]
        pooled = (h * w).sum(axis=1) / jnp.maximum(w.sum(axis=1), 1.0)

    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def init_embedder_params_quantized(
    cfg: ModelConfig, key: jax.Array, scale_dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random-init the encoder tree DIRECTLY in int8-quantized form — the
    bf16 tree of an 8B-class embedder (~15 GB) never materializes on a
    16 GB chip (same scheme as quant.py:init_llama_params_quantized:
    uniform int8 payloads, fan_in**-0.5 / 73.3 per-output-channel scales).
    Biases and norms stay in `scale_dtype` (qdot quantizes matmuls only)."""
    from .quant import qw_random

    hd = cfg.resolved_head_dim
    L, D, H, F, V = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.ffn_hidden, cfg.vocab_size
    keys = jax.random.split(key, 16)
    kit = iter(keys)

    def qw(shape, fan_in, scale_axes):
        return qw_random(next(kit), shape, fan_in, scale_axes, scale_dtype)

    layers: Params = {
        "attn_norm": jnp.ones((L, D), dtype=scale_dtype),
        "wq": qw((L, D, H * hd), D, (L, H * hd)),
        "wk": qw((L, D, H * hd), D, (L, H * hd)),
        "wv": qw((L, D, H * hd), D, (L, H * hd)),
        "wo": qw((L, H * hd, D), H * hd, (L, D)),
        "ffn_norm": jnp.ones((L, D), dtype=scale_dtype),
        "w1": qw((L, D, F), D, (L, F)),
        "w2": qw((L, F, D), F, (L, D)),
    }
    if cfg.enc_gated:
        layers["w3"] = qw((L, D, F), D, (L, F))
    if cfg.enc_norm == "layer":
        layers["attn_norm_b"] = jnp.zeros((L, D), dtype=scale_dtype)
        layers["ffn_norm_b"] = jnp.zeros((L, D), dtype=scale_dtype)
    if cfg.enc_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype=scale_dtype)
        layers["bk"] = jnp.zeros((L, H * hd), dtype=scale_dtype)
        layers["bv"] = jnp.zeros((L, H * hd), dtype=scale_dtype)
        layers["bo"] = jnp.zeros((L, D), dtype=scale_dtype)
        layers["b1"] = jnp.zeros((L, F), dtype=scale_dtype)
        layers["b2"] = jnp.zeros((L, D), dtype=scale_dtype)
        if cfg.enc_gated:
            layers["b3"] = jnp.zeros((L, F), dtype=scale_dtype)

    params: Params = {
        "embed": qw((V, D), D, (V,)),  # per-row scales (embed_lookup contract)
        "layers": layers,
    }
    if cfg.enc_pos == "learned":
        params["pos_embed"] = jnp.zeros((cfg.max_seq_len, D), dtype=scale_dtype)
    if cfg.type_vocab_size:
        params["type_embed"] = jnp.zeros((cfg.type_vocab_size, D), dtype=scale_dtype)
    if cfg.enc_post_ln:
        params["embed_norm"] = jnp.ones((D,), dtype=scale_dtype)
        if cfg.enc_norm == "layer":
            params["embed_norm_b"] = jnp.zeros((D,), dtype=scale_dtype)
    else:
        params["final_norm"] = jnp.ones((D,), dtype=scale_dtype)
    return params
