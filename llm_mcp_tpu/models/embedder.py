"""Bidirectional transformer encoder for embeddings, HBM-resident.

Replaces the reference's delegated Ollama `/api/embed` batch path
(`core/internal/api/handlers.go:1942-2015`) and `ollama.embed` jobs
(`worker/llm_worker/main.py:246-261`) with an in-process encoder serving
`POST /v1/embeddings` directly from TPU. Same TPU-first conventions as
models/llama.py: scan over layers, static shapes, bf16 with f32 reductions.

Matryoshka `dimensions` truncation (reference `handlers.go:2063-2078` does
client-side truncation as a fallback) is exact here: truncate then
re-normalize — done in the engine so one forward pass serves any requested
dimension.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..ops.norms import rms_norm as _rms_norm
from ..ops.rope import rope_tables, apply_rope
from .configs import ModelConfig
from .quant import embed_lookup, qdot

Params = dict[str, Any]


def init_embedder_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    hd = cfg.resolved_head_dim
    L, D, H, F, V = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.ffn_hidden, cfg.vocab_size
    keys = jax.random.split(key, 8)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dtype)

    return {
        "embed": w(keys[0], (V, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype=dtype),
            "wq": w(keys[1], (L, D, H * hd), D),
            "wk": w(keys[2], (L, D, H * hd), D),
            "wv": w(keys[3], (L, D, H * hd), D),
            "wo": w(keys[4], (L, H * hd, D), H * hd),
            "ffn_norm": jnp.ones((L, D), dtype=dtype),
            "w1": w(keys[5], (L, D, F), D),
            "w3": w(keys[6], (L, D, F), D),
            "w2": w(keys[7], (L, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype=dtype),
    }


def embed_forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32 right-padded
    lengths: jnp.ndarray,  # [B] int32
) -> jnp.ndarray:
    """Encode a batch → L2-normalized embeddings [B, D] float32."""
    B, S = tokens.shape
    hd = cfg.resolved_head_dim
    H = cfg.n_heads

    h = embed_lookup(params["embed"], tokens)
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]
    cos, sin = rope_tables(cfg, hd, positions)

    valid = jnp.arange(S)[None, :] < lengths[:, None]  # [B, S]
    mask = valid[:, None, :]  # [B, 1(q), S(k)] — bidirectional, pad-masked
    neg = jnp.float32(-1e30)

    def layer(h, lp):
        # qdot keeps int8 weight trees transparent (w8a8 on the MXU) — the
        # 8B-class embedder only fits a 16 GB chip quantized
        x = _rms_norm(h, lp["attn_norm"], cfg.norm_eps)
        q = qdot(x, lp["wq"]).reshape(B, S, H, hd)
        k = qdot(x, lp["wk"]).reshape(B, S, H, hd)
        v = qdot(x, lp["wv"]).reshape(B, S, H, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (hd**-0.5)
        scores = jnp.where(mask[:, None, :, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H * hd)
        h = h + qdot(ctx, lp["wo"])

        x = _rms_norm(h, lp["ffn_norm"], cfg.norm_eps)
        gate = jax.nn.silu(qdot(x, lp["w1"]))
        up = qdot(x, lp["w3"])
        h = h + qdot(gate * up, lp["w2"])
        return h, None

    h, _ = jax.lax.scan(layer, h, params["layers"])
    h = _rms_norm(h, params["final_norm"], cfg.norm_eps).astype(jnp.float32)

    if cfg.pooling == "cls":
        pooled = h[:, 0]
    else:  # masked mean
        w = valid.astype(jnp.float32)[:, :, None]
        pooled = (h * w).sum(axis=1) / jnp.maximum(w.sum(axis=1), 1.0)

    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def init_embedder_params_quantized(
    cfg: ModelConfig, key: jax.Array, scale_dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random-init the encoder tree DIRECTLY in int8-quantized form — the
    bf16 tree of an 8B-class embedder (~15 GB) never materializes on a
    16 GB chip (same scheme as quant.py:init_llama_params_quantized:
    uniform int8 payloads, fan_in**-0.5 / 73.3 per-output-channel scales)."""
    from .quant import qw_random

    hd = cfg.resolved_head_dim
    L, D, H, F, V = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.ffn_hidden, cfg.vocab_size
    keys = jax.random.split(key, 16)
    kit = iter(keys)

    def qw(shape, fan_in, scale_axes):
        return qw_random(next(kit), shape, fan_in, scale_axes, scale_dtype)

    return {
        "embed": qw((V, D), D, (V,)),  # per-row scales (embed_lookup contract)
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype=scale_dtype),
            "wq": qw((L, D, H * hd), D, (L, H * hd)),
            "wk": qw((L, D, H * hd), D, (L, H * hd)),
            "wv": qw((L, D, H * hd), D, (L, H * hd)),
            "wo": qw((L, H * hd, D), H * hd, (L, D)),
            "ffn_norm": jnp.ones((L, D), dtype=scale_dtype),
            "w1": qw((L, D, F), D, (L, F)),
            "w3": qw((L, D, F), D, (L, F)),
            "w2": qw((L, F, D), F, (L, D)),
        },
        "final_norm": jnp.ones((D,), dtype=scale_dtype),
    }
