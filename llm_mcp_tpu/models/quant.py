"""Weight-only int8 quantization for the serving path.

TPU decode is HBM-bandwidth-bound: every step streams all weights once, so
halving weight bytes nearly halves step time. Per-output-channel symmetric
int8 (the standard weight-only scheme: negligible quality loss, no
activation calibration needed) stores each linear as `{"q": int8, "s":
bf16-scale}`; the matmul reads int8 from HBM and XLA fuses the int8→bf16
convert into the operand load, so VMEM/MXU still run bf16 × bf16 → f32.

Parity note: the reference's executor (Ollama/llama.cpp) serves q4/q8 GGUF
models by default — quantized inference is its normal operating mode, and
this module is that capability rebuilt TPU-style. (`worker/llm_worker/
main.py:222-243` merely proxies; quantization lived inside the native
dependency.)

Scales are per-OUTPUT-channel so dequantization commutes with the matmul:
    x @ (q * s[None, :]) == (x @ q) * s
which keeps the int8 tensor the only weight-sized HBM read.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# linear weights quantized inside each stacked layer pytree: [L, in, out]
LAYER_QUANT_KEYS = (
    "wq", "wk", "wv", "wo", "w1", "w2", "w3",
    # MLA factorization (models/mla.py): qdot consumes these transparently;
    # the absorbed decode dequantizes w_ukv once per step
    "wq_mla", "w_dkv", "w_ukv", "wo_mla",
    # DeepSeek shared experts — dense always-on linears (models/moe.py
    # routes them through qdot); the ROUTED expert banks stay unquantized
    "w1s", "w3s", "w2s",
    # single-chip fused layouts (fuse_layer_weights): wqkv = [wq|wk|wv],
    # w13 = [w1|w3] concatenated along the output axis post-quantization
    "wqkv", "w13",
)


def _quantize_slice(w: jnp.ndarray, axis: int) -> dict[str, jnp.ndarray]:
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": jnp.squeeze(scale, axis=axis).astype(w.dtype)}


def quantize_weight(w: jnp.ndarray, axis: int = -2) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8: reduce |max| over the CONTRACTION
    axis (default -2 = the `in` dim of an [..., in, out] linear). Scales
    keep the weight's dtype, so f32 test models stay f32 end-to-end.

    Stacked [L, in, out] tensors are quantized one layer-slice at a time:
    the f32 working copy is 2x the bf16 weight, and at engine init the full
    bf16 tree is still resident — a whole-tensor upcast of e.g. Llama-8B's
    stacked FFN (3.8 GB bf16) would spike ~8 GB and OOM the exact
    single-chip deployments int8 exists to enable. Per-slice, the transient
    is 1/L of that."""
    if w.ndim >= 3:
        parts = [_quantize_slice(w[i], axis) for i in range(w.shape[0])]
        return {
            "q": jnp.stack([p["q"] for p in parts]),
            "s": jnp.stack([p["s"] for p in parts]),
        }
    return _quantize_slice(w, axis)


import os

_W8A8 = os.environ.get("LLM_MCP_TPU_W8A8", "1") != "0"


def qdot(x: jnp.ndarray, w) -> jnp.ndarray:
    """Matmul over the last axis of x; transparent for plain arrays.

    For quantized weights the default path quantizes the ACTIVATION rows to
    int8 too (w8a8): the MXU consumes the int8 weight payload directly
    (s8 x s8 -> s32), so the weight-sized HBM read is never converted
    elementwise. The convert path (`LLM_MCP_TPU_W8A8=0`) runs int8->bf16 on
    the VPU at ~1 elem/lane/cycle — about HBM byte rate — which nearly
    doubles decode step time at 8B (measured: ~17 ms/step floor vs ~11).
    Per-row activation scales x per-output-channel weight scales rescale the
    int32 accumulator, llama.cpp-q8_0 style.
    """
    if isinstance(w, dict):
        if _W8A8:
            xf = x.astype(jnp.float32)
            xa = jnp.maximum(
                jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, 1e-30
            )
            x8 = jnp.round(xf / xa).astype(jnp.int8)
            y = jax.lax.dot_general(
                x8,
                w["q"],
                (((x8.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            return (y.astype(jnp.float32) * xa * w["s"].astype(jnp.float32)).astype(
                x.dtype
            )
        y = jnp.matmul(x, w["q"].astype(x.dtype))
        return y * w["s"].astype(y.dtype)
    return jnp.matmul(x, w)


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w


def embed_lookup(embed, tokens: jnp.ndarray) -> jnp.ndarray:
    """Embedding rows for token ids; per-ROW scales when quantized. The
    activation dtype follows the scale dtype (model compute dtype)."""
    if isinstance(embed, dict):
        rows = embed["q"][tokens].astype(embed["s"].dtype)
        return rows * embed["s"][tokens][..., None]
    return embed[tokens]


def logits_head(embed_or_head, h: jnp.ndarray, tied: bool) -> jnp.ndarray:
    """Final projection to vocab logits (f32). For tied embeddings the table
    is [V, D] with per-V-row scales == per-output-channel of its transpose."""
    if isinstance(embed_or_head, dict):
        q, s = embed_or_head["q"], embed_or_head["s"]
        m = q.T if tied else q
        y = jnp.matmul(h, m.astype(h.dtype)).astype(jnp.float32)
        return y * s.astype(jnp.float32)
    head = embed_or_head.T if tied else embed_or_head
    return jnp.einsum("...d,dv->...v", h, head).astype(jnp.float32)


def quantize_params(params: Params) -> Params:
    """Quantize all dense linears (+ the embedding/LM head) of a Llama-family
    param tree in place-compatible form. Norm weights stay bf16 (tiny, and
    precision-sensitive); MoE expert banks stay unquantized (their dispatch
    einsums in models/moe.py have their own path) — on MoE models only the
    attention linears and embedding quantize."""
    def quant_block(block: Params) -> Params:
        b = dict(block)
        for k in LAYER_QUANT_KEYS:
            if k in b and not is_quantized(b[k]):
                b[k] = quantize_weight(b[k])
        return b

    out: Params = dict(params)
    out["layers"] = quant_block(params["layers"])
    if "dense_layers" in params:  # DeepSeek first-dense prologue stack
        out["dense_layers"] = quant_block(params["dense_layers"])
    if not is_quantized(params["embed"]):
        # per-row (vocab) scales: contraction axis for the tied head is D,
        # but the LOOKUP needs row scales; per-row also equals per-output-
        # channel of embed.T, which is exactly what the tied logits head
        # contracts against.
        out["embed"] = quantize_weight(params["embed"], axis=-1)
    if "lm_head" in params and not is_quantized(params["lm_head"]):
        out["lm_head"] = quantize_weight(params["lm_head"], axis=-2)
    return out


def qw_random(key, shape, fan_in, scale_axes, scale_dtype) -> dict:
    """Direct-int8 random weight: uniform int8 payload + constant
    per-output-channel scales. Uniform int8 draws have std ≈ 73.3, so
    fan_in**-0.5 / 73.3 matches the fan-in-scaled normal init's magnitude.
    Single source of truth for every direct-quantized init (the llama tree
    below, models/embedder.py's encoder tree)."""
    q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int8)
    s = jnp.full(scale_axes, (fan_in**-0.5) / 73.3, dtype=scale_dtype)
    return {"q": q, "s": s}


def init_llama_params_quantized(
    cfg, key: jax.Array, scale_dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random-init a Llama-family param tree DIRECTLY in int8-quantized form
    (the tree shape `quantize_params` produces), never materializing the
    bf16 tree.

    Exists for models too large to init-then-quantize on one chip: 8B bf16
    is 16 GB — the whole HBM of a v5e — while the int8 tree it quantizes to
    is half that. Benchmarks and engine boots without a checkpoint use this
    for 8B-class configs. Uniform int8 draws have std ≈ 73.3, so the
    per-channel scale is fan_in**-0.5 / 73.3 to match `init_llama_params`'s
    fan-in-scaled normal init.
    """
    from .configs import ModelConfig  # noqa: F401 (type only)

    hd = cfg.resolved_head_dim
    L, D, H, Hkv, F, V = (
        cfg.n_layers,
        cfg.dim,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.ffn_hidden,
        cfg.vocab_size,
    )
    # DeepSeek first-dense split (see models/mla.py:init_mla_params): the
    # main stack holds L - k layers; a dense_layers prologue holds the rest
    k_dense = (
        cfg.first_dense_layers
        if (cfg.n_experts and getattr(cfg, "kv_lora_rank", 0))
        else 0
    )
    L = L - k_dense
    keys = jax.random.split(key, 24)
    kit = iter(keys)

    def qw(shape, fan_in, scale_axes):
        return qw_random(next(kit), shape, fan_in, scale_axes, scale_dtype)

    norm_init = jnp.full((L, D), 1.0 - cfg.norm_weight_offset, dtype=scale_dtype)
    layers: Params = {"attn_norm": norm_init, "ffn_norm": norm_init}
    def mla_attn_q(depth: int) -> Params:
        # the quantized analog of mla.py:_mla_attn_weights, depth-
        # parameterized so the main stack and the dense prologue share it
        dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        R = cfg.kv_lora_rank
        return {
            "wq_mla": qw((depth, D, H * (dn + dr)), D, (depth, H * (dn + dr))),
            "w_dkv": qw((depth, D, R + dr), D, (depth, R + dr)),
            "kv_norm": jnp.ones((depth, R), dtype=scale_dtype),
            "w_ukv": qw((depth, R, H * (dn + dv)), R, (depth, H * (dn + dv))),
            "wo_mla": qw((depth, H * dv, D), H * dv, (depth, D)),
        }

    if getattr(cfg, "kv_lora_rank", 0):
        # MLA factorized attention (models/mla.py), direct-int8 — the
        # latent down-projection's RMSNorm weight stays full precision
        if getattr(cfg, "q_lora_rank", 0):
            # same guard as init_mla_params: a silent dense-q tree would be
            # the wrong architecture for a V2/V3-layout config
            raise ValueError(
                "q_lora_rank > 0 (low-rank query path) is not implemented; "
                "use the dense-q MLA variant (q_lora_rank=0, V2-Lite style)"
            )
        layers.update(mla_attn_q(L))
    else:
        layers.update(
            {
                "wq": qw((L, D, H * hd), D, (L, H * hd)),
                "wk": qw((L, D, Hkv * hd), D, (L, Hkv * hd)),
                "wv": qw((L, D, Hkv * hd), D, (L, Hkv * hd)),
                "wo": qw((L, H * hd, D), H * hd, (L, D)),
            }
        )
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype=scale_dtype)
        layers["bk"] = jnp.zeros((L, Hkv * hd), dtype=scale_dtype)
        layers["bv"] = jnp.zeros((L, Hkv * hd), dtype=scale_dtype)
    if cfg.qk_norm:
        # Qwen3 per-head q/k RMSNorm weights stay full precision
        layers["q_norm"] = jnp.ones((L, hd), dtype=scale_dtype)
        layers["k_norm"] = jnp.ones((L, hd), dtype=scale_dtype)
    if cfg.post_norms:
        layers["post_attn_norm"] = norm_init
        layers["post_ffn_norm"] = norm_init
    if cfg.n_experts:
        # routed expert banks stay unquantized (quantize_params parity);
        # shared experts are dense linears and quantize like any other
        from .llama import init_moe_layer_params

        moe_p = init_moe_layer_params(cfg, next(kit), scale_dtype, n_layers=L)
        for sk in ("w1s", "w3s", "w2s"):
            if sk in moe_p:
                moe_p[sk] = quantize_weight(moe_p[sk])
        layers.update(moe_p)
    else:
        layers.update(
            {
                "w1": qw((L, D, F), D, (L, F)),
                "w3": qw((L, D, F), D, (L, F)),
                "w2": qw((L, F, D), F, (L, D)),
            }
        )
    params: Params = {
        "embed": {
            "q": jax.random.randint(next(kit), (V, D), -127, 128, dtype=jnp.int8),
            "s": jnp.full((V,), (D**-0.5) / 73.3, dtype=scale_dtype),
        },
        "layers": layers,
        "final_norm": jnp.full((D,), 1.0 - cfg.norm_weight_offset, dtype=scale_dtype),
    }
    if k_dense:
        dnorm = jnp.full((k_dense, D), 1.0 - cfg.norm_weight_offset, dtype=scale_dtype)
        params["dense_layers"] = {
            "attn_norm": dnorm,
            "ffn_norm": dnorm,
            **mla_attn_q(k_dense),
            "w1": qw((k_dense, D, F), D, (k_dense, F)),
            "w3": qw((k_dense, D, F), D, (k_dense, F)),
            "w2": qw((k_dense, F, D), F, (k_dense, D)),
        }
    if not cfg.tie_embeddings:
        params["lm_head"] = qw((D, V), D, (V,))
    return params


def quantized_specs(specs: Params) -> Params:
    """Map a param PartitionSpec tree (parallel/sharding.py:llama_param_specs)
    onto the quantized tree shape: `q` keeps the weight's spec, `s` drops the
    contracted axis (scales are per-output-channel, so their sharding is the
    weight's spec minus the reduced dim). Lets TP-sharded serving run int8 —
    the v5e-8 baseline config — instead of carving quantization out for
    meshes."""
    from jax.sharding import PartitionSpec as P

    def drop(spec, axis: int):
        t = list(spec)
        del t[axis]
        return P(*t)

    def quant_block_specs(block):
        b = dict(block)
        for k in LAYER_QUANT_KEYS:
            if k in b:
                b[k] = {"q": b[k], "s": drop(b[k], -2)}
        return b

    out: Params = dict(specs)
    out["layers"] = quant_block_specs(specs["layers"])
    if "dense_layers" in specs:
        out["dense_layers"] = quant_block_specs(specs["dense_layers"])
    out["embed"] = {"q": specs["embed"], "s": drop(specs["embed"], -1)}
    if "lm_head" in specs:
        out["lm_head"] = {"q": specs["lm_head"], "s": drop(specs["lm_head"], -2)}
    return out


def _concat_w(parts):
    """Concatenate linears along the OUTPUT axis, preserving quantization.

    Post-quantization concat is exact for the w8a8 path: `qdot` quantizes
    the activation row once per call (per-row amax over the shared input),
    so a fused s8xs8 dot produces bit-identical int32 columns to running
    the separate dots — the fusion only changes how many times the scan
    body launches a matmul and re-reads the activation, never the math."""
    if all(isinstance(p, dict) for p in parts):
        return {
            "q": jnp.concatenate([p["q"] for p in parts], axis=-1),
            "s": jnp.concatenate([p["s"] for p in parts], axis=-1),
        }
    if any(isinstance(p, dict) for p in parts):
        raise ValueError("cannot fuse mixed quantized/unquantized linears")
    return jnp.concatenate(parts, axis=-1)


def fuse_layer_weights(params: Params) -> Params:
    """Rewrite a layer stack for the single-chip decode hot path: the three
    QKV projections become one `wqkv` dot and the two gate/up FFN
    projections one `w13` dot. The layer `lax.scan` then issues 2 big
    matmuls instead of 5 small ones per block half, which raises achieved
    HBM bandwidth on the w8a8 pass (fewer kernel launches + activation
    re-reads per weight byte; NOTES_r05 measured the unfused pass at
    ~570 GB/s of the 819 GB/s roofline).

    Single-chip only: the fused output axis interleaves q|k|v head groups,
    which the `tp` axis of `llama_param_specs` cannot shard — the engine
    gates the call on `mesh is None`. MoE stacks keep w1/w3 unfused (they
    have none); MLA stacks fuse only w13. Consumers: `llama._qkv` /
    `llama._ffn_residual` detect "wqkv"/"w13" and split the fused output.
    """

    def fuse_block(block: Params) -> Params:
        b = dict(block)
        if all(k in b for k in ("wq", "wk", "wv")):
            b["wqkv"] = _concat_w([b.pop("wq"), b.pop("wk"), b.pop("wv")])
            if all(k in b for k in ("bq", "bk", "bv")):
                b["bqkv"] = jnp.concatenate(
                    [b.pop("bq"), b.pop("bk"), b.pop("bv")], axis=-1
                )
        if "w1" in b and "w3" in b:
            b["w13"] = _concat_w([b.pop("w1"), b.pop("w3")])
        return b

    out: Params = dict(params)
    out["layers"] = fuse_block(params["layers"])
    if "dense_layers" in params:
        out["dense_layers"] = fuse_block(params["dense_layers"])
    return out


def scan_unroll() -> int:
    """Unroll factor for the decode layer scans (`LLM_MCP_TPU_SCAN_UNROLL`).

    A modest unroll (default 4 on TPU) amortizes the per-iteration scan
    overhead (dynamic-slice of the stacked weights + loop bookkeeping)
    without the 32x program bloat of full unrolling — the middle ground
    NOTES_r05 asked for between scan-per-layer and `unroll=n_layers`.
    CPU/interpret runs keep 1: unrolling only slows compilation there."""
    import jax as _jax

    on_tpu = any(d.platform == "tpu" for d in _jax.devices())
    return int(os.environ.get("LLM_MCP_TPU_SCAN_UNROLL", "4" if on_tpu else "1"))


def scale_pack_width(n_kv_heads: int, head_dim: int, scale_dtype) -> int:
    """Padded head rows needed to ride per-position dequant scales inside
    the int8 KV payload block: 1 when the 2*Hkv k+v scale bytes for one
    position fit a single head_dim lane row, else 0 (packing disabled —
    the blocked kernel falls back to a second scale DMA per cell)."""
    it = jnp.dtype(scale_dtype).itemsize
    return 1 if 2 * n_kv_heads * it <= head_dim else 0


def pack_scales(s: jnp.ndarray, head_dim: int) -> jnp.ndarray:
    """Bit-pack per-position scales [..., Hs, T] into one int8 pseudo-head
    row [..., 1, T, head_dim] so the blocked attention kernel's single
    payload DMA carries the dequant scales with the int8 K/V rows.

    Layout per position (lane axis): Hs scales of `s.dtype`, byte-exact via
    bitcast, then zero padding to head_dim lanes. The kernel inverts this
    with `unpack_scales` after the block lands in VMEM."""
    Hs, T = s.shape[-2], s.shape[-1]
    it = jnp.dtype(s.dtype).itemsize
    sw = jnp.swapaxes(s, -1, -2)  # [..., T, Hs]
    raw = jax.lax.bitcast_convert_type(sw, jnp.int8)  # [..., T, Hs, it]
    raw = raw.reshape(*sw.shape[:-1], Hs * it)
    pad = [(0, 0)] * (raw.ndim - 1) + [(0, head_dim - Hs * it)]
    return jnp.pad(raw, pad)[..., None, :, :]  # [..., 1, T, head_dim]


def unpack_scales(row: jnp.ndarray, n_heads: int, scale_dtype) -> jnp.ndarray:
    """Invert `pack_scales` for one landed block: [..., T, head_dim] int8
    -> [..., n_heads, T] scales. Runs inside the kernel (VMEM-resident
    bitcast on a [T, Hs*itemsize] tile) and in tests."""
    it = jnp.dtype(scale_dtype).itemsize
    raw = row[..., : n_heads * it]
    raw = raw.reshape(*row.shape[:-1], n_heads, it)
    s = jax.lax.bitcast_convert_type(raw, scale_dtype)  # [..., T, n_heads]
    return jnp.swapaxes(s, -1, -2)


def quantized_bytes(params: Params) -> tuple[int, int]:
    """(bytes_quantized_tree, bytes_bf16_equivalent) for logging."""

    def nbytes(t) -> int:
        return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(t))

    def bf16_bytes(t) -> int:
        return sum(x.size * 2 for x in jax.tree_util.tree_leaves(t))

    return nbytes(params), bf16_bytes(params)
