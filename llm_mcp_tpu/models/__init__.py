from .configs import config_from_hf, config_from_hf_dir, resolve_config, ModelConfig, MODEL_CONFIGS, get_config
from .llama import init_llama_params, llama_prefill, llama_decode_step, init_kv_cache
from .embedder import init_embedder_params, embed_forward
from .weights import (
    read_safetensors,
    write_safetensors,
    read_checkpoint_dir,
    hf_to_llama_params,
    llama_to_hf_tensors,
    load_llama_checkpoint,
    place_params,
    save_native,
    load_native,
)

__all__ = [
    "read_safetensors",
    "write_safetensors",
    "read_checkpoint_dir",
    "hf_to_llama_params",
    "llama_to_hf_tensors",
    "load_llama_checkpoint",
    "place_params",
    "save_native",
    "load_native",
    "ModelConfig",
    "MODEL_CONFIGS",
    "get_config",
    "config_from_hf",
    "config_from_hf_dir",
    "resolve_config",
    "init_llama_params",
    "llama_prefill",
    "llama_decode_step",
    "init_kv_cache",
    "init_embedder_params",
    "embed_forward",
]
