from .configs import ModelConfig, MODEL_CONFIGS, get_config
from .llama import init_llama_params, llama_prefill, llama_decode_step, init_kv_cache
from .embedder import init_embedder_params, embed_forward

__all__ = [
    "ModelConfig",
    "MODEL_CONFIGS",
    "get_config",
    "init_llama_params",
    "llama_prefill",
    "llama_decode_step",
    "init_kv_cache",
    "init_embedder_params",
    "embed_forward",
]
