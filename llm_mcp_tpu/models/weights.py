"""Checkpoint loading: safetensors → sharded HBM, plus native save/restore.

The reference never touches model weights — they live inside external Ollama
servers and "loading a model" is an HTTP-side effect (`discovery.go:482-560`
just catalogs names). In the TPU-native build, weight I/O is a real
subsystem:

  - **safetensors reader/writer** in pure numpy: the format is an 8-byte
    little-endian header length + JSON header + raw tensor bytes, so a
    dependency-free mmap reader is ~60 lines and never copies more than one
    tensor at a time. BF16 is handled via `ml_dtypes` (ships with JAX).
  - **HF name mapping**: `model.layers.{i}.self_attn.q_proj.weight`-style
    checkpoints are re-laid-out into this framework's scan-friendly stacked
    tree (`params["layers"]["wq"]: [L, D, H·hd]`, see models/llama.py). HF
    linears are [out, in]; ours are [in, out] (activations are row vectors),
    so every projection transposes on load.
  - **Sharded placement**: with a mesh, each mapped leaf is `device_put` with
    its `NamedSharding` from parallel/sharding.py — weights stream from host
    RAM straight into sharded HBM; no chip ever materializes the full 8B
    tree.
  - **Native checkpoints** via orbax (`save_native`/`load_native`) for
    engine-produced artifacts (quantized/re-laid-out weights), with an npz
    fallback when orbax is unavailable.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
from typing import Any, Callable

import numpy as np

from .configs import ModelConfig

try:  # ml_dtypes ships with jax; gives numpy a real bfloat16 dtype.
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

# safetensors dtype tag ↔ numpy dtype
_ST_DTYPES: dict[str, np.dtype] = {
    "F64": np.dtype("<f8"),
    "F32": np.dtype("<f4"),
    "F16": np.dtype("<f2"),
    "I64": np.dtype("<i8"),
    "I32": np.dtype("<i4"),
    "I16": np.dtype("<i2"),
    "I8": np.dtype("i1"),
    "U8": np.dtype("u1"),
    "BOOL": np.dtype("?"),
}
if _BF16 is not None:
    _ST_DTYPES["BF16"] = _BF16


def _np_to_st_dtype(dt: np.dtype) -> str:
    for tag, nd in _ST_DTYPES.items():
        if nd == dt:
            return tag
    raise ValueError(f"unsupported dtype for safetensors: {dt}")


def read_safetensors(path: str) -> dict[str, np.ndarray]:
    """Read every tensor from one .safetensors file (zero-copy mmap views)."""
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode("utf-8"))
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    base = 8 + hlen
    out: dict[str, np.ndarray] = {}
    for name, spec in header.items():
        if name == "__metadata__":
            continue
        dt = _ST_DTYPES.get(spec["dtype"])
        if dt is None:
            raise ValueError(f"{path}: tensor {name} has unsupported dtype {spec['dtype']}")
        b, e = spec["data_offsets"]
        arr = np.frombuffer(mm, dtype=dt, count=(e - b) // dt.itemsize, offset=base + b)
        out[name] = arr.reshape(spec["shape"])
    return out


def write_safetensors(path: str, tensors: dict[str, np.ndarray]) -> None:
    """Write tensors to one .safetensors file (for tests and re-export)."""
    header: dict[str, Any] = {}
    offset = 0
    blobs: list[bytes] = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        blob = arr.tobytes()
        header[name] = {
            "dtype": _np_to_st_dtype(arr.dtype),
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(blob)],
        }
        offset += len(blob)
        blobs.append(blob)
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    # Pad header to 8-byte alignment (spec allows trailing spaces).
    pad = (8 - (len(hjson) % 8)) % 8
    hjson += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for blob in blobs:
            f.write(blob)


def read_checkpoint_dir(ckpt_dir: str) -> dict[str, np.ndarray]:
    """Merge all *.safetensors shards in a directory (HF multi-shard layout)."""
    files = sorted(
        os.path.join(ckpt_dir, f)
        for f in os.listdir(ckpt_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {ckpt_dir}")
    tensors: dict[str, np.ndarray] = {}
    for f in files:
        tensors.update(read_safetensors(f))
    return tensors


# ---------------------------------------------------------------------------
# HF llama-family name mapping → stacked scan layout
# ---------------------------------------------------------------------------

# (our layer key, HF suffix, transpose?) — HF stores linears [out, in].
_LLAMA_LAYER_MAP = [
    ("attn_norm", "input_layernorm.weight", False),
    ("wq", "self_attn.q_proj.weight", True),
    ("wk", "self_attn.k_proj.weight", True),
    ("wv", "self_attn.v_proj.weight", True),
    ("wo", "self_attn.o_proj.weight", True),
    ("ffn_norm", "post_attention_layernorm.weight", False),
    ("w1", "mlp.gate_proj.weight", True),
    ("w3", "mlp.up_proj.weight", True),
    ("w2", "mlp.down_proj.weight", True),
]


def _layer_map(cfg: ModelConfig) -> list[tuple[str, str, bool]]:
    """Per-family HF suffix map. NB the naming trap: in llama/qwen/mistral
    checkpoints `post_attention_layernorm` is the PRE-FFN norm; Gemma2
    (post_norms) uses it for the actual post-attention norm and names the
    pre-FFN norm `pre_feedforward_layernorm`."""
    m = list(_LLAMA_LAYER_MAP)
    if cfg.n_experts:
        m = [e for e in m if e[0] not in ("w1", "w3", "w2")]
    if cfg.post_norms:
        m = [e for e in m if e[0] != "ffn_norm"]
        m += [
            ("ffn_norm", "pre_feedforward_layernorm.weight", False),
            ("post_attn_norm", "post_attention_layernorm.weight", False),
            ("post_ffn_norm", "post_feedforward_layernorm.weight", False),
        ]
    if cfg.qkv_bias:
        m += [
            ("bq", "self_attn.q_proj.bias", False),
            ("bk", "self_attn.k_proj.bias", False),
            ("bv", "self_attn.v_proj.bias", False),
        ]
    if cfg.qk_norm:
        # Qwen3: per-head q/k RMSNorm weights, [head_dim] per layer
        m += [
            ("q_norm", "self_attn.q_norm.weight", False),
            ("k_norm", "self_attn.k_norm.weight", False),
        ]
    return m


# Mixtral-style MoE layers: router + per-expert w1/w2/w3 (HF [out, in]).
_MOE_GATE = "block_sparse_moe.gate.weight"


def _moe_suffix(e: int, w: str) -> str:
    return f"block_sparse_moe.experts.{e}.{w}.weight"


# ---------------------------------------------------------------------------
# DeepSeek-V2 (MLA + shared-expert MoE) name mapping
# ---------------------------------------------------------------------------


def _rope_perm(dr: int, inverse: bool = False) -> np.ndarray:
    """HF DeepseekV2 checkpoints store the rope dims INTERLEAVED (the
    modeling code de-interleaves q_pe/k_pe at runtime via
    view(d//2, 2).transpose); this framework's apply_rope is split-half, so
    the permutation is baked into the weight columns at load time."""
    perm = np.empty(dr, dtype=np.int64)
    perm[: dr // 2] = np.arange(0, dr, 2)
    perm[dr // 2 :] = np.arange(1, dr, 2)
    if inverse:
        inv = np.empty_like(perm)
        inv[perm] = np.arange(dr)
        return inv
    return perm


def _hf_to_mla_layer(
    cfg: ModelConfig, get, prefix: str, i: int
) -> dict[str, np.ndarray]:
    """One DeepSeek-V2 layer's attention + norms from HF tensors into this
    framework's [in, out] orientation (HF linears are [out, in])."""
    H = cfg.n_heads
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    R = cfg.kv_lora_rank
    perm = _rope_perm(dr)
    base = f"{prefix}layers.{i}."

    q = get(base + "self_attn.q_proj.weight").T  # [D, H*(dn+dr)]
    q = q.reshape(-1, H, dn + dr)
    q = np.concatenate([q[..., :dn], q[..., dn:][..., perm]], axis=-1)
    dkv = get(base + "self_attn.kv_a_proj_with_mqa.weight").T  # [D, R+dr]
    dkv = np.concatenate([dkv[..., :R], dkv[..., R:][..., perm]], axis=-1)
    return {
        "attn_norm": get(base + "input_layernorm.weight"),
        "ffn_norm": get(base + "post_attention_layernorm.weight"),
        "wq_mla": q.reshape(-1, H * (dn + dr)),
        "w_dkv": dkv,
        "kv_norm": get(base + "self_attn.kv_a_layernorm.weight"),
        "w_ukv": get(base + "self_attn.kv_b_proj.weight").T,  # [R, H*(dn+dv)]
        "wo_mla": get(base + "self_attn.o_proj.weight").T,  # [H*dv, D]
    }


def _hf_to_mla_params(
    cfg: ModelConfig, get, prefix: str
) -> dict[str, Any]:
    """DeepSeek-V2 layout: dense FFN on layers [0, first_dense_layers),
    shared-expert MoE (mlp.gate / mlp.experts.* / mlp.shared_experts.*) on
    the rest — stacked into the dense_layers/layers split that
    models/mla.py scans (reference analog: the name-only deepseek entries
    `discovery.go:510`; here the architecture actually executes)."""
    k_dense = cfg.first_dense_layers if cfg.n_experts else 0

    def dense_ffn(i: int) -> dict[str, np.ndarray]:
        base = f"{prefix}layers.{i}.mlp."
        return {
            "w1": get(base + "gate_proj.weight").T,
            "w3": get(base + "up_proj.weight").T,
            "w2": get(base + "down_proj.weight").T,
        }

    def moe_ffn_block(i: int) -> dict[str, np.ndarray]:
        base = f"{prefix}layers.{i}.mlp."
        out = {
            "router": get(base + "gate.weight").T,  # [D, E]
            "w1e": np.stack(
                [get(f"{base}experts.{e}.gate_proj.weight").T for e in range(cfg.n_experts)]
            ),
            "w3e": np.stack(
                [get(f"{base}experts.{e}.up_proj.weight").T for e in range(cfg.n_experts)]
            ),
            "w2e": np.stack(
                [get(f"{base}experts.{e}.down_proj.weight").T for e in range(cfg.n_experts)]
            ),
        }
        if cfg.n_shared_experts:
            out["w1s"] = get(base + "shared_experts.gate_proj.weight").T
            out["w3s"] = get(base + "shared_experts.up_proj.weight").T
            out["w2s"] = get(base + "shared_experts.down_proj.weight").T
        return out

    def stack(dicts: list[dict[str, np.ndarray]]) -> dict[str, np.ndarray]:
        return {k: np.stack([d[k] for d in dicts], axis=0) for k in dicts[0]}

    main: list[dict[str, np.ndarray]] = []
    dense: list[dict[str, np.ndarray]] = []
    for i in range(cfg.n_layers):
        lp = _hf_to_mla_layer(cfg, get, prefix, i)
        if i < k_dense:
            lp.update(dense_ffn(i))
            dense.append(lp)
        else:
            lp.update(moe_ffn_block(i) if cfg.n_experts else dense_ffn(i))
            main.append(lp)

    params: dict[str, Any] = {
        "embed": get(f"{prefix}embed_tokens.weight"),
        "layers": stack(main),
        "final_norm": get(f"{prefix}norm.weight"),
    }
    if dense:
        params["dense_layers"] = stack(dense)
    return params  # lm_head filled by the caller's shared fallback logic


def hf_to_llama_params(
    cfg: ModelConfig,
    tensors: dict[str, np.ndarray],
    *,
    prefix: str = "model.",
) -> dict[str, Any]:
    """Re-layout an HF llama/qwen/mixtral-style checkpoint into the stacked
    tree.

    Returns numpy arrays (host RAM); cast + placement happen in
    `place_params`. Raises KeyError with the missing tensor name on an
    incomplete checkpoint.
    """

    def get(name: str) -> np.ndarray:
        if name not in tensors:
            raise KeyError(f"checkpoint missing tensor {name!r}")
        return tensors[name]

    if cfg.kv_lora_rank:  # DeepSeek-V2 MLA family
        params = _hf_to_mla_params(cfg, get, prefix)
        if not cfg.tie_embeddings:
            lm = tensors.get("lm_head.weight")
            params["lm_head"] = (lm if lm is not None else params["embed"]).T
        return params

    L = cfg.n_layers
    layer_map = _layer_map(cfg)
    layers: dict[str, np.ndarray] = {}
    for ours, suffix, transpose in layer_map:
        per_layer = []
        for i in range(L):
            t = get(f"{prefix}layers.{i}.{suffix}")
            per_layer.append(t.T if transpose else t)
        layers[ours] = np.stack(per_layer, axis=0)
    if cfg.n_experts:
        layers["router"] = np.stack(
            [get(f"{prefix}layers.{i}.{_MOE_GATE}").T for i in range(L)], axis=0
        )  # [L, D, E]
        for ours, hf_w in (("w1e", "w1"), ("w2e", "w2"), ("w3e", "w3")):
            layers[ours] = np.stack(
                [
                    np.stack(
                        [
                            get(f"{prefix}layers.{i}.{_moe_suffix(e, hf_w)}").T
                            for e in range(cfg.n_experts)
                        ],
                        axis=0,
                    )
                    for i in range(L)
                ],
                axis=0,
            )  # [L, E, in, out]

    params: dict[str, Any] = {
        "embed": get(f"{prefix}embed_tokens.weight"),
        "layers": layers,
        "final_norm": get(f"{prefix}norm.weight"),
    }
    if not cfg.tie_embeddings:
        lm = tensors.get("lm_head.weight")
        if lm is None:  # some exports tie silently — fall back to embed
            lm = params["embed"]
        params["lm_head"] = lm.T
    return params


def _mla_to_hf_tensors(
    cfg: ModelConfig, params: dict[str, Any], *, prefix: str = "model."
) -> dict[str, np.ndarray]:
    """Inverse of `_hf_to_mla_params` — re-interleaves the rope columns."""
    H = cfg.n_heads
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    R = cfg.kv_lora_rank
    inv = _rope_perm(dr, inverse=True)
    k_dense = cfg.first_dense_layers if cfg.n_experts else 0
    out: dict[str, np.ndarray] = {
        f"{prefix}embed_tokens.weight": np.asarray(params["embed"]),
        f"{prefix}norm.weight": np.asarray(params["final_norm"]),
    }
    if not cfg.tie_embeddings and "lm_head" in params:
        out["lm_head.weight"] = np.asarray(params["lm_head"]).T

    def emit_layer(i: int, block: dict[str, Any], j: int) -> None:
        base = f"{prefix}layers.{i}."
        q = np.asarray(block["wq_mla"][j]).reshape(-1, H, dn + dr)
        q = np.concatenate([q[..., :dn], q[..., dn:][..., inv]], axis=-1)
        dkv = np.asarray(block["w_dkv"][j])
        dkv = np.concatenate([dkv[..., :R], dkv[..., R:][..., inv]], axis=-1)
        out[base + "input_layernorm.weight"] = np.asarray(block["attn_norm"][j])
        out[base + "post_attention_layernorm.weight"] = np.asarray(block["ffn_norm"][j])
        out[base + "self_attn.q_proj.weight"] = q.reshape(-1, H * (dn + dr)).T
        out[base + "self_attn.kv_a_proj_with_mqa.weight"] = dkv.T
        out[base + "self_attn.kv_a_layernorm.weight"] = np.asarray(block["kv_norm"][j])
        out[base + "self_attn.kv_b_proj.weight"] = np.asarray(block["w_ukv"][j]).T
        out[base + "self_attn.o_proj.weight"] = np.asarray(block["wo_mla"][j]).T
        if "router" in block:
            out[base + "mlp.gate.weight"] = np.asarray(block["router"][j]).T
            for e in range(cfg.n_experts):
                out[f"{base}mlp.experts.{e}.gate_proj.weight"] = np.asarray(block["w1e"][j, e]).T
                out[f"{base}mlp.experts.{e}.up_proj.weight"] = np.asarray(block["w3e"][j, e]).T
                out[f"{base}mlp.experts.{e}.down_proj.weight"] = np.asarray(block["w2e"][j, e]).T
            if "w1s" in block:
                out[base + "mlp.shared_experts.gate_proj.weight"] = np.asarray(block["w1s"][j]).T
                out[base + "mlp.shared_experts.up_proj.weight"] = np.asarray(block["w3s"][j]).T
                out[base + "mlp.shared_experts.down_proj.weight"] = np.asarray(block["w2s"][j]).T
        else:
            out[base + "mlp.gate_proj.weight"] = np.asarray(block["w1"][j]).T
            out[base + "mlp.up_proj.weight"] = np.asarray(block["w3"][j]).T
            out[base + "mlp.down_proj.weight"] = np.asarray(block["w2"][j]).T

    for j in range(k_dense):
        emit_layer(j, params["dense_layers"], j)
    for j in range(cfg.n_layers - k_dense):
        emit_layer(k_dense + j, params["layers"], j)
    return out


def llama_to_hf_tensors(
    cfg: ModelConfig, params: dict[str, Any], *, prefix: str = "model."
) -> dict[str, np.ndarray]:
    """Inverse of `hf_to_llama_params` (for re-export / roundtrip tests)."""
    if cfg.kv_lora_rank:
        return _mla_to_hf_tensors(cfg, params, prefix=prefix)
    out: dict[str, np.ndarray] = {
        f"{prefix}embed_tokens.weight": np.asarray(params["embed"]),
        f"{prefix}norm.weight": np.asarray(params["final_norm"]),
    }
    layer_map = _layer_map(cfg)
    for ours, suffix, transpose in layer_map:
        stacked = np.asarray(params["layers"][ours])
        for i in range(cfg.n_layers):
            t = stacked[i]
            out[f"{prefix}layers.{i}.{suffix}"] = t.T if transpose else t
    if cfg.n_experts:
        router = np.asarray(params["layers"]["router"])  # [L, D, E]
        for i in range(cfg.n_layers):
            out[f"{prefix}layers.{i}.{_MOE_GATE}"] = router[i].T
            for ours, hf_w in (("w1e", "w1"), ("w2e", "w2"), ("w3e", "w3")):
                stacked = np.asarray(params["layers"][ours])  # [L, E, in, out]
                for e in range(cfg.n_experts):
                    out[f"{prefix}layers.{i}.{_moe_suffix(e, hf_w)}"] = stacked[i, e].T
    if not cfg.tie_embeddings and "lm_head" in params:
        out["lm_head.weight"] = np.asarray(params["lm_head"]).T
    return out


# ---------------------------------------------------------------------------
# HF encoder-family (BERT/nomic) name mapping → stacked scan layout
# ---------------------------------------------------------------------------

# (our key, HF layer suffix, transpose?) for classic BERT checkpoints
# (google-bert/*, sentence-transformers exports; optional "bert." prefix).
_BERT_LAYER_MAP = [
    ("wq", "attention.self.query.weight", True),
    ("bq", "attention.self.query.bias", False),
    ("wk", "attention.self.key.weight", True),
    ("bk", "attention.self.key.bias", False),
    ("wv", "attention.self.value.weight", True),
    ("bv", "attention.self.value.bias", False),
    ("wo", "attention.output.dense.weight", True),
    ("bo", "attention.output.dense.bias", False),
    ("attn_norm", "attention.output.LayerNorm.weight", False),
    ("attn_norm_b", "attention.output.LayerNorm.bias", False),
    ("w1", "intermediate.dense.weight", True),
    ("b1", "intermediate.dense.bias", False),
    ("w2", "output.dense.weight", True),
    ("b2", "output.dense.bias", False),
    ("ffn_norm", "output.LayerNorm.weight", False),
    ("ffn_norm_b", "output.LayerNorm.bias", False),
]


def hf_to_embedder_params(
    cfg: ModelConfig, tensors: dict[str, np.ndarray]
) -> dict[str, Any]:
    """Re-layout an HF encoder checkpoint (BERT or nomic_bert naming) into
    the stacked tree models/embedder.py scans over.

    Classic BERT: `encoder.layer.{i}.attention.self.query.weight`-style,
    with an optional `bert.` prefix. nomic_bert: flash-attn style
    `encoder.layers.{i}.attn.Wqkv.weight` (fused qkv, split on load) with
    post-LN norms as `norm1`/`norm2`. The gated MLP's fc11/fc12 split the
    fused flash-attn GatedMlp fc1, whose forward chunks into (y, gate) and
    applies the activation to the SECOND chunk: fc11 is the multiplicative
    path (our w3), fc12 the activated gate (our w1). Raises KeyError naming
    the missing tensor on an incomplete checkpoint."""
    prefix = "bert." if any(k.startswith("bert.") for k in tensors) else ""

    def get(name: str) -> np.ndarray:
        t = tensors.get(prefix + name)
        if t is None:
            raise KeyError(f"checkpoint missing tensor {prefix + name!r}")
        return t

    def opt(name: str) -> np.ndarray | None:
        return tensors.get(prefix + name)

    L, D = cfg.n_layers, cfg.dim
    nomic = any(".attn.Wqkv." in k for k in tensors)
    layers: dict[str, list[np.ndarray]] = {}

    def push(key: str, t: np.ndarray) -> None:
        layers.setdefault(key, []).append(t)

    for i in range(L):
        if nomic:
            base = f"encoder.layers.{i}."
            wqkv = get(base + "attn.Wqkv.weight")  # [3D, D] fused, HF [out, in]
            q, k, v = np.split(wqkv, 3, axis=0)
            push("wq", q.T), push("wk", k.T), push("wv", v.T)
            bqkv = opt(base + "attn.Wqkv.bias")
            if cfg.enc_bias:
                if bqkv is None:
                    raise KeyError(f"checkpoint missing tensor {base}attn.Wqkv.bias")
                bq, bk, bv = np.split(bqkv, 3, axis=0)
                push("bq", bq), push("bk", bk), push("bv", bv)
                push("bo", get(base + "attn.out_proj.bias"))
                push("b1", get(base + "mlp.fc12.bias"))
                push("b3", get(base + "mlp.fc11.bias"))
                push("b2", get(base + "mlp.fc2.bias"))
            push("wo", get(base + "attn.out_proj.weight").T)
            push("attn_norm", get(base + "norm1.weight"))
            push("attn_norm_b", get(base + "norm1.bias"))
            # fc12 feeds the activation (our w1), fc11 the multiplicative
            # path (our w3) — flash-attn chunk order, see docstring
            push("w1", get(base + "mlp.fc12.weight").T)
            push("w3", get(base + "mlp.fc11.weight").T)
            push("w2", get(base + "mlp.fc2.weight").T)
            push("ffn_norm", get(base + "norm2.weight"))
            push("ffn_norm_b", get(base + "norm2.bias"))
        else:
            base = f"encoder.layer.{i}."
            for ours, suffix, transpose in _BERT_LAYER_MAP:
                if ours in ("bq", "bk", "bv", "bo", "b1", "b2") and not cfg.enc_bias:
                    continue
                if ours in ("attn_norm_b", "ffn_norm_b") and cfg.enc_norm != "layer":
                    continue
                t = get(base + suffix)
                push(ours, t.T if transpose else t)

    params: dict[str, Any] = {
        "embed": get("embeddings.word_embeddings.weight"),
        "layers": {k: np.stack(v, axis=0) for k, v in layers.items()},
    }
    if cfg.enc_pos == "learned":
        pos = get("embeddings.position_embeddings.weight")
        params["pos_embed"] = pos[: cfg.max_seq_len]
    if cfg.type_vocab_size:
        params["type_embed"] = get("embeddings.token_type_embeddings.weight")
    if cfg.enc_post_ln:
        ew = opt("emb_ln.weight") if nomic else opt("embeddings.LayerNorm.weight")
        eb = opt("emb_ln.bias") if nomic else opt("embeddings.LayerNorm.bias")
        if ew is None or eb is None:
            raise KeyError("checkpoint missing embedding LayerNorm tensors")
        params["embed_norm"], params["embed_norm_b"] = ew, eb
    else:
        params["final_norm"] = get("final_norm.weight")
    return params


def encoder_to_hf_tensors(
    cfg: ModelConfig, params: dict[str, Any], *, naming: str = "bert"
) -> dict[str, np.ndarray]:
    """Inverse of `hf_to_embedder_params` (roundtrip tests / re-export).
    `naming` picks the checkpoint dialect: "bert" (separate q/k/v) or
    "nomic" (fused Wqkv + fc11/fc12)."""
    lt = {k: np.asarray(v) for k, v in params["layers"].items()}
    out: dict[str, np.ndarray] = {
        "embeddings.word_embeddings.weight": np.asarray(params["embed"]),
    }
    if "pos_embed" in params:
        out["embeddings.position_embeddings.weight"] = np.asarray(params["pos_embed"])
    if "type_embed" in params:
        out["embeddings.token_type_embeddings.weight"] = np.asarray(params["type_embed"])
    if cfg.enc_post_ln:
        ln_w, ln_b = "emb_ln.weight", "emb_ln.bias"
        if naming == "bert":
            ln_w, ln_b = "embeddings.LayerNorm.weight", "embeddings.LayerNorm.bias"
        out[ln_w] = np.asarray(params["embed_norm"])
        out[ln_b] = np.asarray(params["embed_norm_b"])
    else:
        out["final_norm.weight"] = np.asarray(params["final_norm"])
    for i in range(cfg.n_layers):
        if naming == "nomic":
            base = f"encoder.layers.{i}."
            out[base + "attn.Wqkv.weight"] = np.concatenate(
                [lt["wq"][i].T, lt["wk"][i].T, lt["wv"][i].T], axis=0
            )
            if cfg.enc_bias:
                out[base + "attn.Wqkv.bias"] = np.concatenate(
                    [lt["bq"][i], lt["bk"][i], lt["bv"][i]], axis=0
                )
                out[base + "attn.out_proj.bias"] = lt["bo"][i]
                out[base + "mlp.fc12.bias"] = lt["b1"][i]
                out[base + "mlp.fc11.bias"] = lt["b3"][i]
                out[base + "mlp.fc2.bias"] = lt["b2"][i]
            out[base + "attn.out_proj.weight"] = lt["wo"][i].T
            out[base + "norm1.weight"] = lt["attn_norm"][i]
            out[base + "norm1.bias"] = lt["attn_norm_b"][i]
            out[base + "mlp.fc12.weight"] = lt["w1"][i].T
            out[base + "mlp.fc11.weight"] = lt["w3"][i].T
            out[base + "mlp.fc2.weight"] = lt["w2"][i].T
            out[base + "norm2.weight"] = lt["ffn_norm"][i]
            out[base + "norm2.bias"] = lt["ffn_norm_b"][i]
        else:
            base = f"encoder.layer.{i}."
            for ours, suffix, transpose in _BERT_LAYER_MAP:
                if ours not in lt:
                    continue
                t = lt[ours][i]
                out[base + suffix] = t.T if transpose else t
    return out


def load_embedder_checkpoint(
    cfg: ModelConfig,
    ckpt_dir: str,
    *,
    dtype: Any = None,
    mesh: Any = None,
) -> Any:
    """One-call load for encoder checkpoints: HF safetensors dir →
    (sharded) device param tree (the encoder analog of
    `load_llama_checkpoint`)."""
    tensors = read_checkpoint_dir(ckpt_dir)
    host = hf_to_embedder_params(cfg, tensors)
    specs = None
    if mesh is not None:
        from ..parallel.sharding import embedder_param_specs

        specs = embedder_param_specs(cfg)
    return place_params(host, dtype=dtype, mesh=mesh, specs=specs)


# ---------------------------------------------------------------------------
# Device placement (optionally sharded)
# ---------------------------------------------------------------------------


def place_params(
    params: Any,
    *,
    dtype: Any = None,
    mesh: Any = None,
    specs: Any = None,
) -> Any:
    """Cast host arrays and put them on device — sharded when a mesh is given.

    Each leaf goes straight to its final `NamedSharding`; XLA transfers only
    the owned shard bytes per device, so a v5e chip never needs host→HBM room
    for the whole tree.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.tree_util import tree_map

    if mesh is not None and specs is not None:
        flat_specs = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: hasattr(x, "index") or x is None
        )
        flat, treedef = jax.tree_util.tree_flatten(params)
        placed = []
        for leaf, spec in zip(flat, flat_specs):
            arr = jnp.asarray(leaf, dtype=dtype) if dtype is not None else jnp.asarray(leaf)
            placed.append(jax.device_put(arr, NamedSharding(mesh, spec)))
        return jax.tree_util.tree_unflatten(treedef, placed)
    cast: Callable[[Any], Any] = (
        (lambda x: jnp.asarray(x, dtype=dtype)) if dtype is not None else jnp.asarray
    )
    return tree_map(cast, params)


def load_llama_checkpoint(
    cfg: ModelConfig,
    ckpt_dir: str,
    *,
    dtype: Any = None,
    mesh: Any = None,
) -> Any:
    """One-call load: HF safetensors dir → (sharded) device param tree."""
    tensors = read_checkpoint_dir(ckpt_dir)
    host = hf_to_llama_params(cfg, tensors)
    specs = None
    if mesh is not None:
        from ..parallel.sharding import llama_param_specs

        specs = llama_param_specs(cfg)
    return place_params(host, dtype=dtype, mesh=mesh, specs=specs)


# ---------------------------------------------------------------------------
# Native checkpoints (orbax, npz fallback)
# ---------------------------------------------------------------------------


def save_native(path: str, params: Any) -> str:
    """Persist a param tree. Orbax layout when available, else a flat npz.

    Returns the path actually written (orbax writes a directory, npz a file
    with `.npz` appended)."""
    path = os.path.abspath(path)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, params, force=True)
        ckptr.wait_until_finished()
        return path
    except ModuleNotFoundError:  # pragma: no cover
        flat = _flatten("", params)
        np.savez(path, **{k: np.asarray(v) for k, v in flat.items()})
        return path + ".npz"


def load_native(
    path: str, *, dtype: Any = None, mesh: Any = None, specs: Any = None
) -> Any:
    """Restore a tree written by `save_native`, optionally sharding it."""
    path = os.path.abspath(path)
    if os.path.isdir(path):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        params = ckptr.restore(path)
    else:
        npz = np.load(path if path.endswith(".npz") else path + ".npz")
        params = _unflatten(dict(npz))
    return place_params(params, dtype=dtype, mesh=mesh, specs=specs)


def _flatten(prefix: str, tree: Any) -> dict[str, Any]:
    if isinstance(tree, dict):
        out: dict[str, Any] = {}
        for k, v in tree.items():
            out.update(_flatten(f"{prefix}{k}/", v))
        return out
    return {prefix[:-1]: tree}


def _unflatten(flat: dict[str, Any]) -> Any:
    tree: dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree
