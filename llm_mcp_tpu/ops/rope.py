"""Rotary position embeddings (RoPE).

TPU-first notes: frequencies are computed inside the jitted graph from static
config (no host round-trips); rotation is pure elementwise VPU work that XLA
fuses into the surrounding matmuls. Split-half convention (as in Llama).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float, positions: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for given integer positions.

    positions: [...,] int32 → returns cos, sin of shape [..., head_dim//2].
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate pairs (split-half layout). x: [..., n_heads, head_dim];
    cos/sin: [..., head_dim//2] broadcast over the heads axis."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
