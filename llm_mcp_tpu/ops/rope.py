"""Rotary position embeddings (RoPE), plain and yarn-scaled.

TPU-first notes: frequencies are computed inside the jitted graph from static
config (no host round-trips) — the yarn correction is pure static math that
folds into the same constants; rotation is elementwise VPU work that XLA
fuses into the surrounding matmuls. Split-half convention (as in Llama).
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float, positions: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for given integer positions.

    positions: [...,] int32 → returns cos, sin of shape [..., head_dim//2].
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def _yarn_get_mscale(scale: float, mscale: float) -> float:
    if scale <= 1.0 or not mscale:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def yarn_rope_frequencies(
    head_dim: int,
    theta: float,
    positions: jnp.ndarray,
    *,
    factor: float,
    orig_max: int,
    beta_fast: float = 32.0,
    beta_slow: float = 1.0,
    mscale: float = 0.0,
    mscale_all_dim: float = 0.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Yarn-corrected cos/sin tables (DeepSeek-V2 long-context rope).

    Per-frequency blend between extrapolation (original inv_freq — kept for
    the high-frequency dims whose wavelength fits inside the original
    context) and interpolation (inv_freq / factor — for the low-frequency
    dims that would otherwise see out-of-distribution angles), with a linear
    ramp between the beta_fast/beta_slow correction dims, and the yarn
    attention-magnitude correction folded into cos/sin.
    """
    half = head_dim // 2
    idx = jnp.arange(0, half, dtype=jnp.float32)
    freq_extra = 1.0 / (theta ** (idx / half))
    freq_inter = freq_extra / factor

    def corr_dim(n_rot: float) -> float:
        return (head_dim * math.log(orig_max / (n_rot * 2 * math.pi))) / (
            2 * math.log(theta)
        )

    low = max(math.floor(corr_dim(beta_fast)), 0)
    high = min(math.ceil(corr_dim(beta_slow)), head_dim - 1)
    ramp = jnp.clip((idx - low) / max(high - low, 1e-3), 0.0, 1.0)
    extra_mask = 1.0 - ramp  # 1 → keep original (extrapolate), 0 → interpolate
    inv_freq = freq_inter * ramp + freq_extra * extra_mask

    m = _yarn_get_mscale(factor, mscale) / _yarn_get_mscale(factor, mscale_all_dim)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles) * m, jnp.sin(angles) * m


def llama3_rope_frequencies(
    head_dim: int,
    theta: float,
    positions: jnp.ndarray,
    *,
    factor: float,
    orig_max: int,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Llama-3.1-style rope scaling (HF rope_type "llama3"): wavelengths
    shorter than orig_max/high_freq_factor keep the original frequency,
    longer than orig_max/low_freq_factor divide by `factor`, and the band
    between interpolates smoothly. No magnitude correction (unlike yarn)."""
    half = head_dim // 2
    idx = jnp.arange(0, half, dtype=jnp.float32)
    inv_freq = 1.0 / (theta ** (idx / half))
    wavelen = 2.0 * math.pi / inv_freq
    low_wl = orig_max / low_freq_factor
    high_wl = orig_max / high_freq_factor
    smooth = jnp.clip(
        (orig_max / wavelen - low_freq_factor)
        / max(high_freq_factor - low_freq_factor, 1e-3),
        0.0,
        1.0,
    )
    blended = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    inv_freq = jnp.where(
        wavelen < high_wl, inv_freq,
        jnp.where(wavelen > low_wl, inv_freq / factor, blended),
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def rope_tables(cfg, head_dim: int, positions: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Config-dispatched rope tables: yarn (DeepSeek-V2) or llama3
    (Llama-3.x long context) when configured, plain otherwise. The single
    entry point every forward path uses."""
    if cfg.rope_factor > 1.0 and cfg.rope_type == "linear":
        # position interpolation: every frequency divides by the factor
        # (orig_max not needed — the scaling is uniform)
        cos, sin = rope_frequencies(
            head_dim, cfg.rope_theta,
            positions.astype(jnp.float32) / cfg.rope_factor,
        )
        return cos, sin
    if cfg.rope_factor > 1.0 and cfg.rope_orig_max:
        if cfg.rope_type == "llama3":
            return llama3_rope_frequencies(
                head_dim,
                cfg.rope_theta,
                positions,
                factor=cfg.rope_factor,
                orig_max=cfg.rope_orig_max,
                low_freq_factor=cfg.llama3_low_freq_factor,
                high_freq_factor=cfg.llama3_high_freq_factor,
            )
        return yarn_rope_frequencies(
            head_dim,
            cfg.rope_theta,
            positions,
            factor=cfg.rope_factor,
            orig_max=cfg.rope_orig_max,
            beta_fast=cfg.yarn_beta_fast,
            beta_slow=cfg.yarn_beta_slow,
            mscale=cfg.yarn_mscale,
            mscale_all_dim=cfg.yarn_mscale_all_dim,
        )
    return rope_frequencies(head_dim, cfg.rope_theta, positions)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate pairs (split-half layout). x: [..., n_heads, head_dim];
    cos/sin: [..., head_dim//2] broadcast over the heads axis."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
