"""Normalization ops shared by the decoder (models/llama.py) and the
embedding encoder (models/embedder.py).

TPU note: the reduction runs in float32 (rsqrt of a bf16 sum loses too much
precision at dim≥4096) and the result is cast back to the activation dtype so
the surrounding matmuls stay bf16 on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * weight.astype(jnp.float32)).astype(x.dtype)
