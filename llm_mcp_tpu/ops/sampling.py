"""On-device token sampling: temperature / top-k / top-p, per-row parameters.

TPU-first design: sampling runs inside the jitted decode step so only the
sampled token ids ([B] int32) ever leave the device — the [B, vocab] logits
never cross HBM→host. A full-vocab sort per step would be wasteful on a 128k
vocab, so top-p operates within a fixed 64-candidate top-k window. For large
vocabs the window itself comes from the TPU-native `lax.approx_max_k`
(recall ~0.95; exact `lax.top_k` costs ~1.5 ms/step at B=64 on a 128k
vocab), so sampling is approximate twice over: the window may miss ~5% of
true top-64 ids, and top-p truncates within it. Greedy (temperature <= 0)
stays exact — it argmaxes the full logits row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_CANDIDATES = 64


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = disabled)
    top_p: jnp.ndarray,  # [B] float32 (1.0 = disabled)
) -> jnp.ndarray:
    """Sample one token per row. temperature<=0 → greedy argmax."""
    B, V = logits.shape
    n_cand = min(_CANDIDATES, V)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Top-K candidate window (per-row k applied by masking within the window).
    # approx_max_k uses the TPU-native approximate top-k (recall ~0.95 within
    # the window) — exact lax.top_k over a 128k vocab costs ~1.5 ms/step at
    # B=64, several times the logits head itself. Results come back sorted
    # descending, which the top-p prefix logic below relies on.
    if V > 4 * n_cand:
        cand_logits, cand_idx = jax.lax.approx_max_k(
            logits, n_cand, recall_target=0.95, aggregate_to_topk=True
        )
    else:
        cand_logits, cand_idx = jax.lax.top_k(logits, n_cand)  # [B, C] desc
    k = jnp.where(top_k <= 0, n_cand, jnp.minimum(top_k, n_cand))
    pos = jnp.arange(n_cand)[None, :]
    k_mask = pos < k[:, None]

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = jnp.where(k_mask, cand_logits / temp, -jnp.inf)

    # Top-p within the window: keep the smallest prefix with cumprob >= p
    # (always keep the first candidate).
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p_mask = (cum - probs) < top_p[:, None]  # prefix-exclusive cumsum < p
    p_mask = p_mask.at[:, 0].set(True)
    final = jnp.where(p_mask & k_mask, scaled, -jnp.inf)

    gumbel = jax.random.gumbel(rng, (B, n_cand), dtype=jnp.float32)
    choice = jnp.argmax(final + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(cand_idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)
