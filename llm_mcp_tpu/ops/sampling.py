"""On-device token sampling: temperature / top-k / top-p, per-row parameters.

TPU-first design: sampling runs inside the jitted decode step so only the
sampled token ids ([B] int32) ever leave the device — the [B, vocab] logits
never cross HBM→host. A full-vocab sort per step would be wasteful on a 128k
vocab, so top-p operates within a fixed 64-candidate top-k window. For large
vocabs the window itself comes from the TPU-native `lax.approx_max_k`
(recall ~0.95; exact `lax.top_k` costs ~1.5 ms/step at B=64 on a 128k
vocab), so sampling is approximate twice over: the window may miss ~5% of
true top-64 ids, and top-p truncates within it. Greedy (temperature <= 0)
stays exact — it argmaxes the full logits row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_CANDIDATES = 64


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = disabled)
    top_p: jnp.ndarray,  # [B] float32 (1.0 = disabled)
    active: jnp.ndarray | None = None,  # [B] bool — rows whose sample matters
) -> jnp.ndarray:
    """Sample one token per row. temperature<=0 → greedy argmax.

    Homogeneous batches take exact fast paths picked at RUNTIME (lax.cond —
    sampling params are device-resident per-slot arrays, so the mix isn't
    known at trace time): all-greedy is one argmax, and all plain
    temperature (no top-k/top-p anywhere) is exact Gumbel-argmax over the
    FULL vocab — both cheaper than the candidate-window machinery (measured
    ~1 ms/step at 8B B=112) and the Gumbel path is exact where the window
    is approximate. Mixed batches keep the windowed path below.

    `active` excludes parked/pad rows from the homogeneity reductions:
    those rows carry zero-init or stale params from a prior occupant and
    their sampled token is discarded anyway — without the mask one stale
    slot would silently disable the fast paths at partial occupancy."""
    B, V = logits.shape
    n_cand = min(_CANDIDATES, V)

    def _pred(cond: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(jnp.where(active, cond, True) if active is not None else cond)

    def _all_greedy(_):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _plain_temp(_):
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        g = jax.random.gumbel(rng, (B, V), dtype=jnp.float32)
        return jnp.argmax(logits / temp + g, axis=-1).astype(jnp.int32)

    def _windowed(_):
        return _sample_windowed(logits, rng, temperature, top_k, top_p, n_cand)

    plain = _pred((top_k <= 0) & (top_p >= 1.0) & (temperature > 0.0))
    return jax.lax.cond(
        _pred(temperature <= 0.0),
        _all_greedy,
        lambda _: jax.lax.cond(plain, _plain_temp, _windowed, None),
        None,
    )


def _sample_windowed(
    logits: jnp.ndarray,
    rng: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    n_cand: int,
) -> jnp.ndarray:
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Top-K candidate window (per-row k applied by masking within the window).
    # approx_max_k uses the TPU-native approximate top-k (recall ~0.95 within
    # the window) — exact lax.top_k over a 128k vocab costs ~1.5 ms/step at
    # B=64, several times the logits head itself. Results come back sorted
    # descending, which the top-p prefix logic below relies on.
    if V > 4 * n_cand:
        cand_logits, cand_idx = jax.lax.approx_max_k(
            logits, n_cand, recall_target=0.95, aggregate_to_topk=True
        )
    else:
        cand_logits, cand_idx = jax.lax.top_k(logits, n_cand)  # [B, C] desc
    k = jnp.where(top_k <= 0, n_cand, jnp.minimum(top_k, n_cand))
    pos = jnp.arange(n_cand)[None, :]
    k_mask = pos < k[:, None]

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = jnp.where(k_mask, cand_logits / temp, -jnp.inf)

    # Top-p within the window: keep the smallest prefix with cumprob >= p
    # (always keep the first candidate).
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p_mask = (cum - probs) < top_p[:, None]  # prefix-exclusive cumsum < p
    p_mask = p_mask.at[:, 0].set(True)
    final = jnp.where(p_mask & k_mask, scaled, -jnp.inf)

    gumbel = jax.random.gumbel(rng, (B, n_cand), dtype=jnp.float32)
    choice = jnp.argmax(final + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(cand_idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)
