"""On-device token sampling: temperature / top-k / top-p, per-row parameters.

TPU-first design: sampling runs inside the jitted decode step so only the
sampled token ids ([B] int32) ever leave the device — the [B, vocab] logits
never cross HBM→host. A full-vocab sort per step would be wasteful on a 128k
vocab, so top-p operates within a fixed 64-candidate top-k window. For large
vocabs the window itself comes from the TPU-native `lax.approx_max_k`
(recall ~0.95; exact `lax.top_k` costs ~1.5 ms/step at B=64 on a 128k
vocab), so sampling is approximate twice over: the window may miss ~5% of
true top-64 ids, and top-p truncates within it. Greedy (temperature <= 0)
stays exact — it argmaxes the full logits row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_CANDIDATES = 64


def expand_mask(packed: jnp.ndarray, V: int) -> jnp.ndarray:
    """Unpack a `[..., ceil(V/32)] uint32` token bitmask to `[..., V]` bool.

    Bit layout matches the host-side constrain/masks.py packer: token id
    ``t`` lives at bit ``t & 31`` of word ``t >> 5``. The gather+shift
    compiles to a handful of vector ops — no host round-trip, so the
    packed words are all that crosses PCIe per constrained row."""
    ids = jnp.arange(V, dtype=jnp.uint32)
    word = packed[..., (ids >> 5).astype(jnp.int32)]
    return ((word >> (ids & jnp.uint32(31))) & jnp.uint32(1)).astype(jnp.bool_)


def apply_token_mask(
    logits: jnp.ndarray,  # [B, V] or [A, C, V]
    packed: jnp.ndarray | None,  # [B, W] / [A, C, W] uint32, or None
    bias_ids: jnp.ndarray | None = None,  # [B, NB] int32, -1 = pad
    bias_vals: jnp.ndarray | None = None,  # [B, NB] float32
) -> jnp.ndarray:
    """Constraint mask + `logit_bias` on one static-shape path.

    Bias is scattered densely FIRST (so a bias can reweight within the
    legal set), then illegal tokens go to -inf — a bias can never
    resurrect a token the automaton forbids. Bias rows are per-request
    ([B, NB]) and broadcast across chunk positions for 3-D verify
    logits; pad entries use id -1 (add 0 at column 0, harmless)."""
    V = logits.shape[-1]
    out = logits
    if bias_ids is not None and bias_vals is not None:
        B = bias_ids.shape[0]
        safe = jnp.maximum(bias_ids, 0)
        vals = jnp.where(bias_ids >= 0, bias_vals, 0.0).astype(logits.dtype)
        dense = jnp.zeros((B, V), dtype=logits.dtype)
        dense = dense.at[jnp.arange(B)[:, None], safe].add(vals)
        out = out + (dense[:, None, :] if logits.ndim == 3 else dense)
    if packed is not None:
        out = jnp.where(expand_mask(packed, V), out, -jnp.inf)
    return out


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = disabled)
    top_p: jnp.ndarray,  # [B] float32 (1.0 = disabled)
    active: jnp.ndarray | None = None,  # [B] bool — rows whose sample matters
    exact: bool = False,  # static: force exact top-k windows (constrained rows)
) -> jnp.ndarray:
    """Sample one token per row. temperature<=0 → greedy argmax.

    Homogeneous batches take exact fast paths picked at RUNTIME (lax.cond —
    sampling params are device-resident per-slot arrays, so the mix isn't
    known at trace time): all-greedy is one argmax, and all plain
    temperature (no top-k/top-p anywhere) is exact Gumbel-argmax over the
    FULL vocab — both cheaper than the candidate-window machinery (measured
    ~1 ms/step at 8B B=112) and the Gumbel path is exact where the window
    is approximate. Mixed batches keep the windowed path below.

    `active` excludes parked/pad rows from the homogeneity reductions:
    those rows carry zero-init or stale params from a prior occupant and
    their sampled token is discarded anyway — without the mask one stale
    slot would silently disable the fast paths at partial occupancy."""
    B, V = logits.shape
    n_cand = min(_CANDIDATES, V)

    def _pred(cond: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(jnp.where(active, cond, True) if active is not None else cond)

    def _all_greedy(_):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _plain_temp(_):
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        g = jax.random.gumbel(rng, (B, V), dtype=jnp.float32)
        return jnp.argmax(logits / temp + g, axis=-1).astype(jnp.int32)

    def _windowed(_):
        return _sample_windowed(
            logits, rng, temperature, top_k, top_p, n_cand, exact=exact
        )

    plain = _pred((top_k <= 0) & (top_p >= 1.0) & (temperature > 0.0))
    return jax.lax.cond(
        _pred(temperature <= 0.0),
        _all_greedy,
        lambda _: jax.lax.cond(plain, _plain_temp, _windowed, None),
        None,
    )


def _sample_windowed(
    logits: jnp.ndarray,
    rng: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    n_cand: int,
    exact: bool = False,
) -> jnp.ndarray:
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Top-K candidate window (per-row k applied by masking within the window).
    # approx_max_k uses the TPU-native approximate top-k (recall ~0.95 within
    # the window) — exact lax.top_k over a 128k vocab costs ~1.5 ms/step at
    # B=64, several times the logits head itself. Results come back sorted
    # descending, which the top-p prefix logic below relies on. Constrained
    # rows force `exact`: with a tiny automaton-legal set a 0.95-recall
    # window could miss EVERY legal token and sample from a -inf row.
    if V > 4 * n_cand and not exact:
        cand_logits, cand_idx = jax.lax.approx_max_k(
            logits, n_cand, recall_target=0.95, aggregate_to_topk=True
        )
    else:
        cand_logits, cand_idx = jax.lax.top_k(logits, n_cand)  # [B, C] desc
    k = jnp.where(top_k <= 0, n_cand, jnp.minimum(top_k, n_cand))
    pos = jnp.arange(n_cand)[None, :]
    k_mask = pos < k[:, None]

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = jnp.where(k_mask, cand_logits / temp, -jnp.inf)

    # Top-p within the window: keep the smallest prefix with cumprob >= p
    # (always keep the first candidate).
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p_mask = (cum - probs) < top_p[:, None]  # prefix-exclusive cumsum < p
    p_mask = p_mask.at[:, 0].set(True)
    final = jnp.where(p_mask & k_mask, scaled, -jnp.inf)

    gumbel = jax.random.gumbel(rng, (B, n_cand), dtype=jnp.float32)
    choice = jnp.argmax(final + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(cand_idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)


def spec_verify(
    logits: jnp.ndarray,  # [A, C, V] float32 — position j scores offset j+1
    drafts: jnp.ndarray,  # [A, K] int32 drafted tokens, K = C - 1
    n_draft: jnp.ndarray,  # [A] int32 — valid drafts per row (<= K)
    rng: jax.Array,
    temperature: jnp.ndarray,  # [A]
    top_k: jnp.ndarray,  # [A] int32 (0 = disabled)
    top_p: jnp.ndarray,  # [A] float32 (1.0 = disabled)
    active: jnp.ndarray | None = None,  # [A] bool — rows whose result matters
    exact: bool = False,  # static: force exact top-k windows (constrained rows)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Accept/reject a deterministic draft against the target logits and
    sample the one token that always follows.

    The engine's n-gram drafter is deterministic — it puts probability 1 on
    its proposal — so standard speculative rejection sampling collapses to:
    accept draft ``d`` at position ``j`` with probability ``p_target(d)``
    (greedy rows: exact argmax equality), stop at the first rejection, and
    sample the next token from the RESIDUAL distribution — the target with
    the rejected token zeroed and renormalized. That marginal is exactly the
    target: ``p(d)·1 + (1 - p(d))·p(x)/(1 - p(d)) = p(x)``, so speculation
    never changes what the engine emits, only how many model calls it costs.

    When every draft is accepted the final token is a "bonus" sample from
    the unmasked target at the position after the last draft — `C = K + 1`
    positions of logits guarantee it exists.

    Distribution parity with `sample_tokens` is structural: the same three
    runtime paths (all-greedy / all plain temperature over the full vocab /
    candidate-window for rows with top-k/top-p), so speculative and
    non-speculative decode agree exactly wherever `sample_tokens` itself is
    exact, and share the same window approximation where it is not.

    Returns ``(n_acc [A] int32, final [A] int32)``: emitted tokens for row
    ``a`` are ``drafts[a, :n_acc[a]]`` followed by ``final[a]``.
    """
    A, C, V = logits.shape
    K = C - 1
    n_cand = min(_CANDIDATES, V)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [A, C]
    valid = jnp.arange(K, dtype=jnp.int32)[None, :] < n_draft[:, None]
    is_greedy = temperature <= 0.0
    rng_u, rng_f = jax.random.split(rng)
    u = jax.random.uniform(rng_u, (A, K), dtype=jnp.float32)

    def _pred(cond: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(jnp.where(active, cond, True) if active is not None else cond)

    def _count(acc: jnp.ndarray) -> jnp.ndarray:
        # longest accepted prefix: cumprod zeroes everything past the first
        # rejection
        return jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)

    def _finish(n_acc, sampled):
        pos_greedy = jnp.take_along_axis(greedy_tok, n_acc[:, None], axis=1)[:, 0]
        final = jnp.where(is_greedy, pos_greedy, sampled)
        return n_acc.astype(jnp.int32), final.astype(jnp.int32)

    def _mask_tok(n_acc):
        # the token to zero out of the residual: the first REJECTED draft.
        # When nothing was rejected (n_acc == n_draft) the final sample is
        # the unmasked bonus token — -1 matches no vocab id.
        rej = jnp.take_along_axis(
            drafts, jnp.minimum(n_acc, K - 1)[:, None], axis=1
        )[:, 0]
        return jnp.where(n_acc < n_draft, rej, -1)

    def _all_greedy(_):
        n_acc = _count((greedy_tok[:, :K] == drafts) & valid)
        return _finish(n_acc, jnp.zeros((A,), jnp.int32))

    def _full_vocab(_):
        temp = jnp.maximum(temperature, 1e-6)[:, None, None]
        scaled = logits / temp  # [A, C, V]
        lse = jax.nn.logsumexp(scaled, axis=-1)  # [A, C]
        d_logit = jnp.take_along_axis(
            scaled[:, :K], drafts[..., None], axis=-1
        )[..., 0]
        p_draft = jnp.exp(d_logit - lse[:, :K])  # [A, K]
        acc = jnp.where(is_greedy[:, None], greedy_tok[:, :K] == drafts, u < p_draft)
        n_acc = _count(acc & valid)
        pos_scaled = jnp.take_along_axis(
            scaled, n_acc[:, None, None], axis=1
        )[:, 0]  # [A, V]
        resid = jnp.where(
            jnp.arange(V, dtype=jnp.int32)[None, :] == _mask_tok(n_acc)[:, None],
            -jnp.inf,
            pos_scaled,
        )
        g = jax.random.gumbel(rng_f, (A, V), dtype=jnp.float32)
        return _finish(n_acc, jnp.argmax(resid + g, axis=-1))

    def _windowed(_):
        # the same candidate-window distribution _sample_windowed draws
        # from, applied per chunk position
        flat = logits.reshape(A * C, V)
        if V > 4 * n_cand and not exact:
            cand_logits, cand_idx = jax.lax.approx_max_k(
                flat, n_cand, recall_target=0.95, aggregate_to_topk=True
            )
        else:
            cand_logits, cand_idx = jax.lax.top_k(flat, n_cand)
        cand_logits = cand_logits.reshape(A, C, n_cand)
        cand_idx = cand_idx.reshape(A, C, n_cand).astype(jnp.int32)
        k = jnp.where(top_k <= 0, n_cand, jnp.minimum(top_k, n_cand))
        k_mask = jnp.arange(n_cand)[None, None, :] < k[:, None, None]
        temp = jnp.maximum(temperature, 1e-6)[:, None, None]
        scaled = jnp.where(k_mask, cand_logits / temp, -jnp.inf)
        probs = jax.nn.softmax(scaled, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        p_mask = (cum - probs) < top_p[:, None, None]
        p_mask = p_mask.at[:, :, 0].set(True)
        m = p_mask & k_mask
        wp = jnp.where(m, probs, 0.0)
        norm = jnp.maximum(jnp.sum(wp, axis=-1), 1e-9)  # [A, C]
        match = cand_idx[:, :K] == drafts[:, :, None]  # [A, K, n_cand]
        p_draft = jnp.sum(jnp.where(match, wp[:, :K], 0.0), axis=-1) / norm[:, :K]
        acc = jnp.where(is_greedy[:, None], greedy_tok[:, :K] == drafts, u < p_draft)
        n_acc = _count(acc & valid)
        take = lambda x: jnp.take_along_axis(x, n_acc[:, None, None], axis=1)[:, 0]
        w_scaled, w_idx, w_m = take(scaled), take(cand_idx), take(m)
        resid = jnp.where(
            w_m & (w_idx != _mask_tok(n_acc)[:, None]), w_scaled, -jnp.inf
        )
        g = jax.random.gumbel(rng_f, (A, n_cand), dtype=jnp.float32)
        choice = jnp.argmax(resid + g, axis=-1)
        sampled = jnp.take_along_axis(w_idx, choice[:, None], axis=1)[:, 0]
        return _finish(n_acc, sampled)

    plain = _pred((top_k <= 0) & (top_p >= 1.0))
    return jax.lax.cond(
        _pred(is_greedy),
        _all_greedy,
        lambda _: jax.lax.cond(plain, _full_vocab, _windowed, None),
        None,
    )
