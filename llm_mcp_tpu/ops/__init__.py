from .rope import rope_frequencies, apply_rope
from .sampling import sample_tokens

__all__ = ["rope_frequencies", "apply_rope", "sample_tokens"]
