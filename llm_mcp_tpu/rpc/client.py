"""gRPC client for the core worker protocol.

Duck-type compatible with `worker.client.CoreClient` (register/claim/
heartbeat/complete/fail/report_offline), so a `Worker` can run over either
transport — the reference worker was gRPC-only (`main.py:536-599`).
Heartbeat lease-lost surfaces as `False` exactly like the HTTP client's 409
mapping; FAILED_PRECONDITION on complete/fail maps to TerminalHTTPError so
Worker's error handling is transport-agnostic.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Iterator

import grpc

from ..telemetry import tracing
from ..worker.client import TerminalHTTPError
from .pb import llm_mcp_tpu_pb2 as pb
from .server import SERVICE_NAME, TERMINAL, TRANSFER_SERVICE_NAME

log = logging.getLogger("rpc.client")


def _method(channel: grpc.Channel, name: str, resp_cls, stream: bool = False):
    path = f"/{SERVICE_NAME}/{name}"
    kw = dict(
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )
    return channel.unary_stream(path, **kw) if stream else channel.unary_unary(path, **kw)


class GrpcCoreClient:
    def __init__(self, target: str, *, timeout_s: float = 30.0):
        self.channel = grpc.insecure_channel(target)
        self.timeout_s = timeout_s
        c = self.channel
        self._submit = _method(c, "SubmitJob", pb.Job)
        self._get = _method(c, "GetJob", pb.Job)
        self._stream = _method(c, "StreamJob", pb.Job, stream=True)
        self._register = _method(c, "RegisterWorker", pb.Ack)
        self._claim = _method(c, "ClaimJob", pb.ClaimResponse)
        self._heartbeat = _method(c, "Heartbeat", pb.Ack)
        self._complete = _method(c, "CompleteJob", pb.Ack)
        self._fail = _method(c, "FailJob", pb.FailResponse)
        self._report_metrics = _method(c, "ReportMetrics", pb.Ack)
        self._report_benchmark = _method(c, "ReportBenchmark", pb.Ack)
        self._report_offline = _method(c, "ReportOffline", pb.Ack)

    def close(self) -> None:
        self.channel.close()

    # -- conversions -------------------------------------------------------

    @staticmethod
    def job_to_dict(j: pb.Job) -> dict[str, Any]:
        """Same shape as the HTTP API's job JSON (state.queue.Job.to_dict)."""
        started = {"started_at": j.started_at or None, "finished_at": j.finished_at or None}
        return {
            **started,
            "id": j.id,
            "kind": j.kind,
            "status": j.status,
            "priority": j.priority,
            "payload": json.loads(j.payload_json) if j.payload_json else {},
            "result": json.loads(j.result_json) if j.result_json else None,
            "error": j.error or None,
            "attempts": j.attempts,
            "max_attempts": j.max_attempts,
            "worker_id": j.worker_id or None,
            "device_id": j.device_id or None,
            "lease_until": j.lease_until or None,
            "deadline_at": j.deadline_at or None,
            "created_at": j.created_at,
            "updated_at": j.updated_at,
        }

    def _call(self, fn, req):
        try:
            return fn(req, timeout=self.timeout_s, metadata=self._trace_metadata())
        except grpc.RpcError as e:
            raise self._map_error(e) from e

    @staticmethod
    def _trace_metadata():
        """Trace context as gRPC invocation metadata — the wire analog of
        the HTTP traceparent header."""
        ctx = tracing.current_traceparent()
        return (("traceparent", ctx),) if ctx else None

    @staticmethod
    def _map_error(e: grpc.RpcError) -> Exception:
        """Terminal codes → TerminalHTTPError (worker must not retry);
        everything else → ConnectionError (retryable transport failure)."""
        code = e.code()
        if code in (
            grpc.StatusCode.FAILED_PRECONDITION,
            grpc.StatusCode.INVALID_ARGUMENT,
            grpc.StatusCode.NOT_FOUND,
        ):
            return TerminalHTTPError(GrpcCoreClient._http_status(code), e.details())
        return ConnectionError(f"grpc {code.name}: {e.details()}")

    @staticmethod
    def _http_status(code: grpc.StatusCode) -> int:
        return {
            grpc.StatusCode.FAILED_PRECONDITION: 409,
            grpc.StatusCode.INVALID_ARGUMENT: 400,
            grpc.StatusCode.NOT_FOUND: 404,
        }.get(code, 500)

    # -- worker protocol (CoreClient-compatible) ---------------------------

    def register(self, worker_id: str, name: str = "", kinds: list[str] | None = None) -> None:
        self._call(
            self._register, pb.WorkerInfo(worker_id=worker_id, name=name, kinds=kinds or [])
        )

    def claim(
        self, worker_id: str, kinds: list[str] | None = None, lease_seconds: float = 30.0
    ) -> dict[str, Any] | None:
        resp = self._call(
            self._claim,
            pb.ClaimRequest(worker_id=worker_id, kinds=kinds or [], lease_seconds=lease_seconds),
        )
        return self.job_to_dict(resp.job) if resp.found else None

    def heartbeat(self, job_id: str, worker_id: str, lease_seconds: float = 30.0) -> bool:
        try:
            ack = self._call(
                self._heartbeat,
                pb.HeartbeatRequest(
                    job_id=job_id, worker_id=worker_id, lease_seconds=lease_seconds
                ),
            )
        except TerminalHTTPError as e:
            if e.status == 409:
                return False
            raise
        return ack.ok

    def complete(
        self,
        job_id: str,
        worker_id: str,
        result: dict[str, Any],
        metrics: dict[str, Any] | None = None,
    ) -> None:
        self._call(
            self._complete,
            pb.CompleteRequest(
                job_id=job_id,
                worker_id=worker_id,
                result_json=json.dumps(result),
                metrics_json=json.dumps(metrics or {}),
            ),
        )

    def fail(self, job_id: str, worker_id: str, error: str) -> str:
        resp = self._call(
            self._fail, pb.FailRequest(job_id=job_id, worker_id=worker_id, error=error)
        )
        return resp.status

    def report_offline(self, device_id: str, reason: str = "") -> None:
        """Mark the device offline + requeue its jobs — same effect as the
        HTTP POST /v1/devices/offline side-channel (main.py:180-186)."""
        try:
            self._call(
                self._report_offline,
                pb.OfflineReport(device_id=device_id, reason=reason or "unreachable"),
            )
        except (ConnectionError, TerminalHTTPError):
            log.warning("offline report for %s failed", device_id)

    # -- control surface ---------------------------------------------------

    def submit(
        self,
        kind: str,
        payload: dict[str, Any] | None = None,
        *,
        priority: int = 0,
        max_attempts: int = 0,
        deadline_at: float = 0.0,
    ) -> dict[str, Any]:
        job = self._call(
            self._submit,
            pb.SubmitJobRequest(
                kind=kind,
                payload_json=json.dumps(payload or {}),
                priority=priority,
                max_attempts=max_attempts,
                deadline_at=deadline_at,
            ),
        )
        return self.job_to_dict(job)

    def get(self, job_id: str) -> dict[str, Any]:
        return self.job_to_dict(self._call(self._get, pb.JobRef(id=job_id)))

    def stream(self, job_id: str, timeout_s: float = 120.0) -> Iterator[dict[str, Any]]:
        try:
            for j in self._stream(
                pb.JobRef(id=job_id), timeout=timeout_s, metadata=self._trace_metadata()
            ):
                d = self.job_to_dict(j)
                yield d
                if d["status"] in TERMINAL:
                    return
        except grpc.RpcError as e:
            raise self._map_error(e) from e

    def report_benchmark(
        self,
        device_id: str,
        model_id: str,
        task_type: str,
        *,
        tokens_in: int = 0,
        tokens_out: int = 0,
        latency_ms: float = 0.0,
        tps: float = 0.0,
    ) -> None:
        self._call(
            self._report_benchmark,
            pb.Benchmark(
                device_id=device_id,
                model_id=model_id,
                task_type=task_type,
                tokens_in=tokens_in,
                tokens_out=tokens_out,
                latency_ms=latency_ms,
                tps=tps,
            ),
        )


class GrpcTransferClient:
    """Client for the KV transfer endpoint (rpc/server.py
    KVTransferService): ships a raw migration payload, yields the resumed
    request's events as they stream back. Identity serializers both ways —
    the payload is already self-describing and each response frame is a
    JSON-encoded event."""

    def __init__(self, target: str, *, timeout_s: float = 600.0):
        from .server import KVTransferService

        self.channel = grpc.insecure_channel(
            target, options=KVTransferService.channel_options()
        )
        self.timeout_s = timeout_s
        self._transfer = self.channel.unary_stream(
            f"/{TRANSFER_SERVICE_NAME}/Transfer",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self._prefix_fetch = self.channel.unary_unary(
            f"/{TRANSFER_SERVICE_NAME}/PrefixFetch",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

    def close(self) -> None:
        self.channel.close()

    def transfer(self, payload: bytes) -> Iterator[dict[str, Any]]:
        try:
            for frame in self._transfer(
                payload,
                timeout=self.timeout_s,
                metadata=GrpcCoreClient._trace_metadata(),
            ):
                yield json.loads(frame)
        except grpc.RpcError as e:
            raise ConnectionError(f"grpc {e.code().name}: {e.details()}") from e

    def prefix_fetch(
        self, ids: list[int], *, timeout_s: float | None = None
    ) -> bytes | None:
        """Pull the peer's longest resident prefix chain for these prompt
        token ids as a raw wire payload. None on a clean miss (NOT_FOUND /
        prefix tier disabled); other failures raise ConnectionError so the
        caller can fall back to recompute AND note the peer as flaky."""
        try:
            return self._prefix_fetch(
                json.dumps({"ids": [int(x) for x in ids]}).encode(),
                timeout=timeout_s if timeout_s is not None else self.timeout_s,
                metadata=GrpcCoreClient._trace_metadata(),
            )
        except grpc.RpcError as e:
            if e.code() in (grpc.StatusCode.NOT_FOUND, grpc.StatusCode.UNIMPLEMENTED):
                return None
            raise ConnectionError(f"grpc {e.code().name}: {e.details()}") from e

    def prefix_fetch_hash(
        self, hash16: str, *, timeout_s: float | None = None
    ) -> bytes | None:
        """Pull the peer's resident chain whose digest head hash matches
        `hash16` (boot-time peer warm-fill: the joining engine knows the
        fleet's hottest head hashes from discovery tags, not the token ids
        behind them). Same miss/failure semantics as prefix_fetch."""
        try:
            return self._prefix_fetch(
                json.dumps({"hash16": str(hash16)}).encode(),
                timeout=timeout_s if timeout_s is not None else self.timeout_s,
                metadata=GrpcCoreClient._trace_metadata(),
            )
        except grpc.RpcError as e:
            if e.code() in (grpc.StatusCode.NOT_FOUND, grpc.StatusCode.UNIMPLEMENTED):
                return None
            raise ConnectionError(f"grpc {e.code().name}: {e.details()}") from e


class RemoteMigrationTarget:
    """Duck-typed migration target for MigrationCoordinator.add_remote: a
    `migrate_import` that ships the payload over the transfer endpoint and
    pumps the response stream back into the original consumer's queue on a
    daemon thread (the coordinator tick must not block on a remote decode).
    The remote engine raising (migration off, bucket too large) surfaces as
    the FAILED_PRECONDITION abort → ConnectionError → an error event."""

    def __init__(self, target: str, *, timeout_s: float = 600.0):
        self.target = target
        self._client = GrpcTransferClient(target, timeout_s=timeout_s)

    def migrate_import(self, payload: bytes, out: Any = None) -> None:
        if out is None:
            raise ValueError("remote migration requires the consumer queue")

        def pump() -> None:
            terminal = False
            try:
                for evt in self._client.transfer(payload):
                    out.put(evt)
                    if evt.get("type") in ("done", "error"):
                        terminal = evt.get("type") == "done"
            except ConnectionError as e:
                out.put({"type": "error", "error": str(e)})
            if not terminal:
                out.put({"type": "done", "finish_reason": "error", "usage": {}})

        threading.Thread(target=pump, name="kv-migrate-pump", daemon=True).start()

    def close(self) -> None:
        self._client.close()
