"""Generated protobuf modules (protoc --python_out)."""
