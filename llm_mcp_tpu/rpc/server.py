"""gRPC core server: the worker protocol over gRPC.

Parity: reference `core/internal/grpcserver/server.go` — 10 RPCs operating
directly on the queue/catalog (never through the HTTP layer): SubmitJob
(26-55), GetJob (57-63), StreamJob (65-96), RegisterWorker (98-124),
ClaimJob (126-198), Heartbeat (200-215), CompleteJob (217-240), FailJob
(242-274), ReportMetrics (276-300), ReportBenchmark (302-327).

Improvements over the reference: StreamJob waits on the queue's update
notification instead of blind 1 s polling (the reference's gRPC stream
lacked the LISTEN path its HTTP SSE twin had, server.go:65-96); ClaimJob
enforces the per-device concurrency cap that the reference's gRPC claim
dropped (SURVEY C9 note).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from concurrent import futures
from contextlib import nullcontext
from typing import Any, Callable

import grpc

from ..state.catalog import Catalog, record_benchmark_from_job
from ..state.jobtrace import record_job_end, record_queue_wait
from ..state.queue import Job, JobQueue
from ..telemetry import tracing
from .pb import llm_mcp_tpu_pb2 as pb

log = logging.getLogger("rpc.server")

SERVICE_NAME = "llmmcptpu.v1.Core"
TRANSFER_SERVICE_NAME = "llmmcptpu.v1.KVTransfer"
TERMINAL = ("done", "error", "canceled")
STREAM_MAX_S = 600.0  # same bound as the HTTP SSE twin (api/jobs.py SSE_MAX_S)
TRANSFER_MAX_BYTES = 1 << 30  # refuse absurd payloads before decoding


def job_to_pb(job: Job) -> pb.Job:
    return pb.Job(
        id=job.id,
        kind=job.kind,
        status=job.status,
        payload_json=json.dumps(job.payload or {}),
        result_json=json.dumps(job.result) if job.result is not None else "",
        error=job.error or "",
        attempts=int(job.attempts),
        max_attempts=int(job.max_attempts),
        worker_id=job.worker_id or "",
        device_id=job.device_id or "",
        priority=int(job.priority),
        created_at=float(job.created_at or 0),
        updated_at=float(job.updated_at or 0),
        lease_until=float(job.lease_until or 0),
        deadline_at=float(job.deadline_at or 0),
        started_at=float(job.started_at or 0),
        finished_at=float(job.finished_at or 0),
    )


class GrpcCoreServer:
    def __init__(
        self,
        queue: JobQueue,
        catalog: Catalog,
        *,
        circuit: Any = None,  # routing.CircuitBreaker | None — shared with the API process
        device_max_concurrency: int = 0,
        default_lease_s: float = 30.0,
        max_workers: int = 16,
    ):
        self.queue = queue
        self.catalog = catalog
        self.circuit = circuit
        self.device_max_concurrency = device_max_concurrency
        self.default_lease_s = default_lease_s
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((self._make_handler(),))
        self.port = 0
        # Long-lived StreamJob handlers each pin an executor thread; cap them
        # to half the pool so Claim/Heartbeat/Complete always have threads
        # (16 parked streams would otherwise starve heartbeats → lease loss).
        self._stream_slots = threading.BoundedSemaphore(max(1, max_workers // 2))

    def enable_kv_transfer(
        self,
        import_stream: Callable[[bytes], Any],
        prefix_export: Callable[[list[int]], bytes | None] | None = None,
        prefix_export_hash: Callable[[str], bytes | None] | None = None,
    ) -> None:
        """Register the KV transfer service on this server — must run
        before start() (gRPC handlers are fixed at server start).
        `prefix_export` additionally serves the PrefixFetch RPC (the
        fleet prefix tier's source side); `prefix_export_hash` extends it
        to digest-head lookups (boot-time peer warm-fill)."""
        self._server.add_generic_rpc_handlers(
            (
                KVTransferService(
                    import_stream,
                    prefix_export=prefix_export,
                    prefix_export_hash=prefix_export_hash,
                ).handler(),
            )
        )

    # -- service wiring (hand-rolled: no grpc_tools plugin in the env) -----

    def _make_handler(self) -> grpc.GenericRpcHandler:
        def unary(fn: Callable, req_cls) -> grpc.RpcMethodHandler:
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        def stream(fn: Callable, req_cls) -> grpc.RpcMethodHandler:
            return grpc.unary_stream_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        handlers = {
            "SubmitJob": unary(self.SubmitJob, pb.SubmitJobRequest),
            "GetJob": unary(self.GetJob, pb.JobRef),
            "StreamJob": stream(self.StreamJob, pb.JobRef),
            "RegisterWorker": unary(self.RegisterWorker, pb.WorkerInfo),
            "ClaimJob": unary(self.ClaimJob, pb.ClaimRequest),
            "Heartbeat": unary(self.Heartbeat, pb.HeartbeatRequest),
            "CompleteJob": unary(self.CompleteJob, pb.CompleteRequest),
            "FailJob": unary(self.FailJob, pb.FailRequest),
            "ReportMetrics": unary(self.ReportMetrics, pb.MetricsReport),
            "ReportBenchmark": unary(self.ReportBenchmark, pb.Benchmark),
            "ReportOffline": unary(self.ReportOffline, pb.OfflineReport),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)

    # -- lifecycle ---------------------------------------------------------

    def start(self, addr: str = "127.0.0.1:0") -> "GrpcCoreServer":
        # Server reflection when grpcio-reflection is installed (grpcurl
        # discovery). The reference DOCUMENTS reflection but never registers
        # it (main.go:92-93, SURVEY C9) — here it's best-effort real.
        try:
            from grpc_reflection.v1alpha import reflection

            reflection.enable_server_reflection(
                (
                    pb.DESCRIPTOR.services_by_name["Core"].full_name,
                    reflection.SERVICE_NAME,
                ),
                self._server,
            )
        except Exception:
            log.debug("grpc reflection unavailable; continuing without it")
        self.port = self._server.add_insecure_port(addr)
        if self.port == 0:
            # grpc signals bind failure by returning port 0 instead of raising
            raise RuntimeError(f"grpc bind failed for {addr!r} (port in use or bad address)")
        self._server.start()
        log.info("grpc server on port %d", self.port)
        return self

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)

    # -- RPCs --------------------------------------------------------------

    def SubmitJob(self, req: pb.SubmitJobRequest, ctx) -> pb.Job:
        # Submits always get a span (joined to the caller's trace when gRPC
        # metadata carries a traceparent, rooted otherwise) — the wire analog
        # of the HTTP layer's root span on POST /v1/jobs.
        tp = self._traceparent(ctx)
        with tracing.get_tracer().span(
            "rpc.SubmitJob", parent=tp or tracing.NEW_TRACE, attrs={"kind": req.kind or "generate"}
        ) as sp:
            try:
                payload = json.loads(req.payload_json) if req.payload_json else {}
            except json.JSONDecodeError:
                ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "payload_json is not valid JSON")
            # same propagation as the HTTP submit path: stamp the trace
            # context into the payload so queue-wait / worker / job-end spans
            # recorded at claim/complete time can join this trace
            ctx_tp = sp.traceparent or tp
            if ctx_tp and "_traceparent" not in payload:
                payload["_traceparent"] = ctx_tp
            job = self.queue.submit(
                req.kind or "generate",
                payload,
                priority=req.priority,
                max_attempts=req.max_attempts or None,
                deadline_at=req.deadline_at or None,
            )
            sp.set_attr("job_id", job.id)
            return job_to_pb(job)

    def GetJob(self, req: pb.JobRef, ctx) -> pb.Job:
        job = self.queue.get(req.id)
        if job is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND, f"job {req.id} not found")
        return job_to_pb(job)

    def StreamJob(self, req: pb.JobRef, ctx):
        """Push the job on every status change until terminal. Wakes on the
        queue's update notification with a 15 s safety re-poll (the behavior
        of the HTTP SSE path, handlers.go:543-577, which the reference's
        gRPC stream lacked)."""
        # version is read BEFORE the job state so an update racing the read
        # makes the next wait return immediately instead of stalling.
        version = self.queue.update_version
        job = self.queue.get(req.id)
        if job is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND, f"job {req.id} not found")
        if not self._stream_slots.acquire(blocking=False):
            # Stream capacity exhausted: degrade to a one-shot status snapshot
            # (clients re-poll GetJob / re-open the stream) instead of parking
            # another executor thread.
            yield job_to_pb(job)
            return
        try:
            last_status = None
            deadline = time.monotonic() + STREAM_MAX_S
            while ctx.is_active() and time.monotonic() < deadline:
                if job is None:
                    return  # job purged mid-stream
                if job.status != last_status:
                    last_status = job.status
                    yield job_to_pb(job)
                    if job.status in TERMINAL:
                        return
                version = self.queue.wait_for_update(15.0, since=version)
                job = self.queue.get(req.id)
        finally:
            self._stream_slots.release()

    def RegisterWorker(self, req: pb.WorkerInfo, ctx) -> pb.Ack:
        if not req.worker_id:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "worker_id required")
        self.catalog.register_worker(req.worker_id, req.name, list(req.kinds))
        return pb.Ack(ok=True, message="registered")

    def ClaimJob(self, req: pb.ClaimRequest, ctx) -> pb.ClaimResponse:
        if not req.worker_id:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "worker_id required")
        with self._rpc_span(ctx, "ClaimJob", {"worker_id": req.worker_id}):
            job = self.queue.claim(
                req.worker_id,
                kinds=list(req.kinds),
                lease_seconds=req.lease_seconds or self.default_lease_s,
                device_max_concurrency=self.device_max_concurrency,
            )
            self.catalog.worker_heartbeat(req.worker_id)
            if job is None:
                return pb.ClaimResponse(found=False)
            record_queue_wait(job, worker_id=req.worker_id)
            return pb.ClaimResponse(found=True, job=job_to_pb(job))

    def Heartbeat(self, req: pb.HeartbeatRequest, ctx) -> pb.Ack:
        ok = self.queue.heartbeat(
            req.job_id, req.worker_id, lease_seconds=req.lease_seconds or self.default_lease_s
        )
        self.catalog.worker_heartbeat(req.worker_id)
        if not ok:
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, "job not running under this worker")
        return pb.Ack(ok=True)

    def CompleteJob(self, req: pb.CompleteRequest, ctx) -> pb.Ack:
        with self._rpc_span(ctx, "CompleteJob", {"job_id": req.job_id}):
            result = self._parse_json(req.result_json, ctx, "result_json")
            metrics = self._parse_json(req.metrics_json, ctx, "metrics_json")
            ok = self.queue.complete(req.job_id, req.worker_id, result=result, metrics=metrics)
            if not ok:
                ctx.abort(
                    grpc.StatusCode.FAILED_PRECONDITION, "job not running under this worker"
                )
            self._post_complete(req.job_id, ok=True)
            return pb.Ack(ok=True)

    def FailJob(self, req: pb.FailRequest, ctx) -> pb.FailResponse:
        with self._rpc_span(ctx, "FailJob", {"job_id": req.job_id}):
            status = self.queue.fail(req.job_id, req.worker_id, req.error or "unknown error")
            if status is None:
                ctx.abort(
                    grpc.StatusCode.FAILED_PRECONDITION, "job not running under this worker"
                )
            self._post_complete(req.job_id, ok=False)
            return pb.FailResponse(status=status)

    def ReportMetrics(self, req: pb.MetricsReport, ctx) -> pb.Ack:
        metrics = self._parse_json(req.metrics_json, ctx, "metrics_json")
        self.catalog.record_device_metrics(req.device_id, metrics or {})
        return pb.Ack(ok=True)

    def ReportOffline(self, req: pb.OfflineReport, ctx) -> pb.Ack:
        """Mark a device offline, open its breaker, and requeue its running
        jobs — the gRPC twin of POST /v1/devices/offline (api/jobs.py
        handle_devices_offline), so the gRPC transport is self-sufficient."""
        if not req.device_id:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "device_id required")
        self.catalog.set_device_online(req.device_id, False)
        if self.circuit is not None:
            self.circuit.record(req.device_id, ok=False)
        requeued = self.queue.requeue_device_jobs([req.device_id])
        return pb.Ack(ok=True, message=f"requeued {requeued}")

    def ReportBenchmark(self, req: pb.Benchmark, ctx) -> pb.Ack:
        if not req.device_id or not req.model_id:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "device_id and model_id required")
        self.catalog.record_benchmark(
            req.device_id,
            req.model_id,
            req.task_type or "generate",
            tokens_in=int(req.tokens_in),
            tokens_out=int(req.tokens_out),
            latency_ms=float(req.latency_ms),
            tps=float(req.tps),
        )
        return pb.Ack(ok=True)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _traceparent(ctx) -> str:
        """Trace context from gRPC invocation metadata — the wire analog of
        the HTTP traceparent header (rpc/client.py attaches it)."""
        for key, value in ctx.invocation_metadata() or ():
            if key == "traceparent":
                return str(value)
        return ""

    def _rpc_span(self, ctx, method: str, attrs: dict[str, Any] | None = None):
        """Server-side span for a worker-protocol RPC, joined to the caller's
        trace. RPCs arriving without a traceparent are not spanned — rooting
        a fresh trace per idle claim poll (every 1.5 s per worker) would
        churn the trace ring with noise."""
        tp = self._traceparent(ctx)
        if not tp:
            return nullcontext()
        return tracing.get_tracer().span(f"rpc.{method}", parent=tp, attrs=attrs)

    def _parse_json(self, text: str, ctx, field: str) -> dict[str, Any] | None:
        if not text:
            return None
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, f"{field} is not valid JSON")
        return doc if isinstance(doc, dict) else {"value": doc}

    def _post_complete(self, job_id: str, ok: bool) -> None:
        """Side effects shared with the HTTP complete/fail path (api/jobs.py):
        circuit-breaker recording for the job's device and benchmark-table
        feeding for benchmark.* kinds — identical across transports."""
        job = self.queue.get(job_id)
        if job is None:
            return
        if job.status in TERMINAL:  # fail() may have requeued for retry
            record_job_end(job, job.status)
        dev = job.payload.get("device_id") or job.device_id
        if dev and self.circuit is not None:
            self.circuit.record(str(dev), ok=ok)
        if ok:
            record_benchmark_from_job(self.catalog, job)


class KVTransferService:
    """Engine-to-engine KV transfer endpoint (executor/migration.py).

    One unary-stream RPC: the request is a raw migration wire payload, the
    response stream is the resumed request's events as JSON frames (token /
    done / error), ending with the terminal event — the source host pumps
    them into the original consumer's queue, so a migrated request streams
    transparently across machines.

    Raw bytes with identity serializers instead of protobuf messages: the
    pb module is a compiled descriptor (no protoc in the env to extend it),
    and the payload is already a self-describing format — wrapping it in a
    `bytes` field would only add a copy. The gRPC max-message default (4 MB)
    is raised to fit whole-bucket snapshots.
    """

    def __init__(
        self,
        import_stream: Callable[[bytes], Any],
        prefix_export: Callable[[list[int]], bytes | None] | None = None,
        prefix_export_hash: Callable[[str], bytes | None] | None = None,
    ):
        # import_stream: engine.migrate_import_stream — payload in, iterator
        # of event dicts out (raises on a payload this engine cannot run)
        # prefix_export: engine.prefix_export — prompt token ids in, wire
        # payload of the longest resident chain out (None on miss)
        # prefix_export_hash: engine.prefix_export_by_hash — digest head
        # hash (16 hex chars) in, whole-chain wire payload out (None on
        # miss). Serves boot-time peer warm-fill, where the requester knows
        # only the fleet digest's head hashes, not the token ids behind them.
        self._import_stream = import_stream
        self._prefix_export = prefix_export
        self._prefix_export_hash = prefix_export_hash
        self._server: grpc.Server | None = None
        self.port = 0

    def handler(self) -> grpc.GenericRpcHandler:
        def transfer(payload: bytes, ctx):
            if len(payload) > TRANSFER_MAX_BYTES:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "payload too large")
            tp = GrpcCoreServer._traceparent(ctx)
            span = (
                tracing.get_tracer().span(
                    "rpc.Transfer", parent=tp, attrs={"bytes": len(payload)}
                )
                if tp
                else nullcontext()
            )
            with span:
                try:
                    events = self._import_stream(payload)
                except (ValueError, RuntimeError) as e:
                    ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
                for evt in events:
                    yield json.dumps(evt).encode()

        def prefix_fetch(request: bytes, ctx) -> bytes:
            # request: JSON {"ids": [prompt token ids]} or
            # {"hash16": "<digest head hash>"} — response: the raw
            # migration-codec payload of this engine's longest resident
            # chain prefixing those ids (resp. the whole chain whose head
            # hash matches). NOT_FOUND on miss keeps the requester's
            # recompute path cheap (no payload decode).
            try:
                req = json.loads(request.decode())
                hash16 = str(req["hash16"]) if "hash16" in req else None
                ids = None if hash16 else [int(x) for x in req["ids"]]
            except (ValueError, KeyError, UnicodeDecodeError) as e:
                ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad prefix request: {e}")
            export = self._prefix_export_hash if hash16 else self._prefix_export
            if export is None:
                ctx.abort(grpc.StatusCode.UNIMPLEMENTED, "prefix tier disabled")
            tp = GrpcCoreServer._traceparent(ctx)
            attrs = {"hash": hash16} if hash16 else {"tokens": len(ids)}
            span = (
                tracing.get_tracer().span("rpc.PrefixFetch", parent=tp, attrs=attrs)
                if tp
                else nullcontext()
            )
            with span:
                payload = export(hash16 if hash16 else ids)
            if payload is None:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no resident prefix")
            return payload

        handlers = {
            "Transfer": grpc.unary_stream_rpc_method_handler(
                transfer,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            ),
            "PrefixFetch": grpc.unary_unary_rpc_method_handler(
                prefix_fetch,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            ),
        }
        return grpc.method_handlers_generic_handler(TRANSFER_SERVICE_NAME, handlers)

    @staticmethod
    def channel_options() -> list[tuple[str, int]]:
        return [
            ("grpc.max_receive_message_length", TRANSFER_MAX_BYTES),
            ("grpc.max_send_message_length", TRANSFER_MAX_BYTES),
        ]

    def start(self, addr: str = "127.0.0.1:0", max_workers: int = 4) -> "KVTransferService":
        """Standalone server for engine-only hosts (no job queue). Engines
        co-hosted with a GrpcCoreServer can instead register `handler()` on
        that server via `GrpcCoreServer.enable_kv_transfer`."""
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=self.channel_options(),
        )
        self._server.add_generic_rpc_handlers((self.handler(),))
        self.port = self._server.add_insecure_port(addr)
        if self.port == 0:
            raise RuntimeError(f"grpc bind failed for {addr!r} (port in use or bad address)")
        self._server.start()
        log.info("kv transfer endpoint on port %d", self.port)
        return self

    def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None
