"""gRPC control/worker protocol.

Parity: reference `core/internal/grpcserver/server.go` (10 RPCs mirroring
the HTTP worker protocol) and `proto/llm.proto` (C9/C14). Messages are
protoc-generated (`pb/llm_mcp_tpu_pb2.py`); service wiring is hand-rolled
with `grpc.method_handlers_generic_handler` because the grpc_tools codegen
plugin is not in the build environment.
"""

from .client import GrpcCoreClient
from .server import GrpcCoreServer

__all__ = ["GrpcCoreServer", "GrpcCoreClient"]
