"""Provider selection: cascade, smart quality routing, device ranking.

Parity map (reference `core/internal/routing/router.go`):
  - RouteLLM cascade (embed→local; force_cloud; prefer_local;
    cloud→local fallback): router.go:126-274
  - SelectOllamaDevice ranking SQL (online ⋈ has-model ⋈ benchmarks ⋈
    limits, ORDER BY tps DESC, latency ASC, last_seen): router.go:277-331
  - routeSmartLLM quality×context-bucket tier mapping: router.go:92-110,407-528
  - token estimation len/4 min 256: router.go:113-123
  - quality deadlines 15..180 s: handlers.go:640-643
  - pricing injection _price_in_1m/_price_out_1m: router.go:513-516

TPU adaptation: the local provider is "tpu" (an in-process or remote TPU
executor device registered in the catalog) instead of an Ollama endpoint;
cloud fallbacks (openrouter/openai) remain HTTP providers.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any

from ..state.catalog import Catalog
from ..state.db import Database
from ..telemetry import tracing
from ..utils.config import getenv
from .circuit import CircuitBreaker
from .limits import (
    LimitsEngine,
    device_headroom,
    device_migration,
    device_prefill_cost,
    device_prefix_digest,
    device_queue_depth,
    device_warming,
)
from .prefix import match_digest, prefix_route_enabled, request_hashes_for

log = logging.getLogger("router")

# Fallbacks for the prefix-locality score when a device hasn't measured
# yet: ~50 us/token is the order of magnitude of 8B-class TPU prefill,
# and one queued request costs roughly one admission round. Both only
# shape *relative* ranking inside a headroom band, so rough is fine.
DEFAULT_PREFILL_S_PER_TOK = 50e-6
QUEUE_PENALTY_S = 0.05

PROVIDER_TPU = "tpu"
PROVIDER_OPENROUTER = "openrouter"
PROVIDER_OPENAI = "openai"

TIER_ORDER = ("turbo", "economy", "standard", "premium", "ultra", "max")

# quality × context-bucket → acceptable local tier lists (best first).
# Mirrors the reference's qualityTiers table (router.go:92-110): bigger
# contexts push toward bigger tiers; low qualities accept smaller models.
QUALITY_TIERS: dict[str, list[list[str]]] = {
    # bucket:      ≤4K                    4-32K                  >32K
    "turbo":    [["turbo", "economy"], ["economy", "standard"], ["standard", "premium"]],
    "economy":  [["economy", "turbo"], ["economy", "standard"], ["standard", "premium"]],
    "standard": [["standard", "economy"], ["standard", "premium"], ["premium", "ultra"]],
    "premium":  [["premium", "standard"], ["premium", "ultra"], ["ultra", "max"]],
    "ultra":    [["ultra", "premium"], ["ultra", "max"], ["max", "ultra"]],
    "max":      [["max", "ultra"], ["max", "ultra"], ["max", "ultra"]],
}

# cloud fallback tiers per quality (router.go cloudFallbackTiers analog)
CLOUD_FALLBACK_TIERS: dict[str, list[str]] = {
    "turbo": ["turbo", "economy", "standard"],
    "economy": ["economy", "standard"],
    "standard": ["standard", "premium"],
    "premium": ["premium", "ultra"],
    "ultra": ["ultra", "max"],
    "max": ["max", "ultra"],
}

# quality → auto job deadline seconds (handlers.go:640-643)
QUALITY_DEADLINES_S: dict[str, float] = {
    "turbo": 15,
    "economy": 30,
    "standard": 60,
    "premium": 90,
    "ultra": 120,
    "max": 180,
}


def estimate_tokens(text: str) -> int:
    """len/4 chars heuristic, floor 256 (router.go:113-123)."""
    return max(len(text) // 4, 256)


def context_bucket(tokens: int) -> int:
    """0: ≤4K, 1: 4-32K, 2: >32K (router.go:420-426)."""
    if tokens <= 4096:
        return 0
    if tokens <= 32_768:
        return 1
    return 2


def quality_deadline_s(quality: str) -> float:
    return QUALITY_DEADLINES_S.get(quality, 60.0)


@dataclass
class RouteDecision:
    provider: str
    kind: str
    model: str = ""
    device_id: str = ""
    device_addr: str = ""
    tier: str = ""
    thinking: bool = False
    reason: str = ""
    extras: dict[str, Any] = field(default_factory=dict)  # merged into job payload

    def payload_overlay(self) -> dict[str, Any]:
        out = dict(self.extras)
        out["provider"] = self.provider
        if self.model:
            out["model"] = self.model
        if self.device_id:
            out["device_id"] = self.device_id
        if self.device_addr:
            out["device_addr"] = self.device_addr
        if self.tier:
            out["_tier"] = self.tier
        if self.thinking:
            out["thinking"] = True
        return out


class Router:
    def __init__(
        self,
        db: Database | None,
        *,
        circuit: CircuitBreaker | None = None,
        limits: LimitsEngine | None = None,
        has_openrouter: bool | None = None,
        has_openai: bool | None = None,
    ):
        # nil-DB construction is legal (the reference does `New(nil)` in
        # tests) — the circuit breaker is memory-only.
        self.db = db
        self.catalog = Catalog(db) if db is not None else None
        self.circuit = circuit or CircuitBreaker()
        self.limits = limits or (LimitsEngine(db) if db is not None else None)
        self.has_openrouter = (
            has_openrouter
            if has_openrouter is not None
            else bool(getenv("OPENROUTER_API_KEY", ""))
        )
        self.has_openai = (
            has_openai if has_openai is not None else bool(getenv("OPENAI_API_KEY", ""))
        )
        # Model zoo (executor/zoo.py), attached by the serving layer when
        # TPU_ZOO_MODELS is set: quality tiers then resolve to a RESIDENT
        # model first, a swappable (parked) one second — the zoo's
        # residency_band supplies the 0/1/2 sort key. None (the default)
        # skips the residency sort entirely: candidate order is
        # byte-identical to the pre-zoo router (stable sorts + no call).
        self.zoo: Any = None

    # -- device selection --------------------------------------------------

    @staticmethod
    def _prefix_score(
        tags: dict, px_ids: list[int] | None, hash_memo: dict
    ) -> tuple[float, int, bool]:
        """Expected-savings score of routing this request to a device
        holding part of its prefix: matched tokens × that device's
        measured prefill cost (PR 12 phase walls, `prefill_us_per_tok`
        tag), minus a queue-depth congestion penalty. Returns
        ``(score_s, matched_tokens, exact)``; all-zero when the device
        advertises no (fresh) digest. Request boundary hashes are memoized
        per block geometry so a fleet scan hashes the prompt once."""
        digest = device_prefix_digest(tags)
        if digest is None or not px_ids:
            return 0.0, 0, False
        bt = int(digest.get("bt", 0) or 0)
        if bt <= 0:
            return 0.0, 0, False
        if bt not in hash_memo:
            hash_memo[bt] = request_hashes_for(digest, px_ids)
        matched, exact = match_digest(digest, hash_memo[bt])
        cost = device_prefill_cost(tags) or DEFAULT_PREFILL_S_PER_TOK
        score = matched * cost - device_queue_depth(tags) * QUEUE_PENALTY_S
        return score, matched, exact

    def select_device(
        self,
        model: str,
        task_type: str = "generate",
        *,
        max_latency_ms: float = 0.0,
        prefix_ids: list[int] | None = None,
    ) -> dict[str, Any] | None:
        """Best online device that has the model, passes limits and circuit,
        ranked by latest benchmark tps DESC, latency ASC, then freshness.

        The one-big-SQL-ranking-query design of the reference
        (router.go:286-322), against the SQLite catalog.
        """
        if self.db is None:
            return None
        # Generation routing also consumes 'serve' rows — REAL client-observed
        # TTFT/tps snapshots the planner records from live engines
        # (planner.record_serve_ttft). The freshest row per (device, model)
        # wins (explicit ROW_NUMBER window, not SQLite's nonstandard
        # bare-column-with-MAX), so during live traffic the measured serving
        # numbers displace stale synthetic benchmarks — but only once the
        # snapshot aggregates enough requests (tokens_out carries the TTFT
        # sample count n): a 10-second tps window over one or two requests
        # must not unseat a full synthetic benchmark.
        alt_type = "serve" if task_type == "generate" else task_type
        try:
            min_serve_n = int(getenv("SERVE_BENCH_MIN_N", "3") or 0)
        except ValueError:
            min_serve_n = 3
        rows = self.db.query(
            """
            SELECT d.id, d.name, d.addr, d.tags, d.last_seen,
                   b.tps AS bench_tps, b.latency_ms AS bench_latency_ms,
                   b.p95_ms AS bench_p95_ms
            FROM devices d
            JOIN device_models dm ON dm.device_id = d.id AND dm.available = 1
            LEFT JOIN (
                SELECT device_id, model_id, tps, latency_ms, p95_ms FROM (
                    SELECT device_id, model_id, tps, latency_ms, p95_ms,
                           ROW_NUMBER() OVER (
                               PARTITION BY device_id, model_id
                               ORDER BY created_at DESC, id DESC
                           ) AS rn
                    FROM benchmarks
                    WHERE task_type IN (?, ?)
                      AND (task_type != 'serve' OR tokens_out >= ?)
                ) WHERE rn = 1
            ) b ON b.device_id = d.id AND b.model_id = dm.model_id
            WHERE d.online = 1 AND dm.model_id = ?
            ORDER BY COALESCE(b.tps, 0) DESC,
                     COALESCE(b.latency_ms, 1e12) ASC,
                     d.last_seen DESC
            """,
            (task_type, alt_type, min_serve_n, model),
        )
        model_row = self.catalog.get_model(model) if self.catalog else None
        ctx_k = int(model_row["context_k"]) if model_row else 0
        # Saturated devices (kv_headroom tag ≤ 0: their KV pool is at the
        # shed watermark and new requests would 429) rank behind everything
        # else regardless of benchmark tps; among the saturated, devices
        # advertising KV migration rank first — they can drain to a peer
        # instead of shedding, so their saturation is transient. Stable
        # sort keeps the SQL tps/latency/freshness order within each band,
        # so a saturated device is still reachable when it's the only one
        # with the model.
        # Prefix locality re-ranks WITHIN a band only: the engine holding
        # the longest resident chain of this prompt wins among its healthy
        # (or equally saturated) peers, but a long cached prefix never
        # outranks headroom — a saturated hit would just shed. With
        # TPU_PREFIX_ROUTE=0 (or no prompt ids) every score is 0.0 and the
        # stable sort reproduces the pre-locality ordering byte-for-byte.
        px_ids = prefix_ids if (prefix_ids and prefix_route_enabled()) else None
        hash_memo: dict[int, list] = {}
        scores: dict[str, tuple[float, int, bool]] = {}

        # A WARMING device (warmup readiness below fully_warm) ranks behind
        # fully-warm healthy peers but ahead of the saturated bands: it
        # serves fine on its compiled critical prefix, yet a fresh shape
        # can still eat a cold XLA compile — reduced capacity, not zero.
        def _band(r) -> tuple[bool, bool, bool, float]:
            tags = Database.from_json(r["tags"], {})
            saturated = device_headroom(tags) <= 0.0
            sc = self._prefix_score(tags, px_ids, hash_memo) if px_ids else (0.0, 0, False)
            scores[r["id"]] = sc
            return (
                saturated and not device_migration(tags), saturated,
                device_warming(tags), -sc[0],
            )

        rows = sorted(rows, key=_band)
        for r in rows:
            dev_id = r["id"]
            if not self.circuit.allow(dev_id):
                continue
            # the latency constraint bites on TAIL latency when the probe
            # measured it (p95, scripts/probe_models.py), else on p50
            eff_latency = r["bench_p95_ms"] or r["bench_latency_ms"] or 0
            if max_latency_ms > 0 and eff_latency > max_latency_ms:
                continue
            if self.limits is not None:
                ok, why = self.limits.model_allowed(dev_id, model, ctx_k)
                if not ok:
                    log.debug("device %s rejected for %s: %s", dev_id, model, why)
                    continue
            r["tags"] = Database.from_json(r["tags"], {})
            sc = scores.get(dev_id, (0.0, 0, False))
            r["prefix_score_s"] = sc[0]
            r["prefix_matched_tokens"] = sc[1]
            r["prefix_match_exact"] = sc[2]
            return r
        return None

    def best_prefix_peer(
        self,
        model: str,
        prefix_ids: list[int],
        *,
        exclude_device: str = "",
        min_tokens: int = 0,
    ) -> tuple[dict[str, Any], int] | None:
        """Peer advertising the longest fresh prefix-chain match for this
        prompt — the remote-fetch probe. Unlike select_device this never
        routes: it only answers "who could we pull KV blocks from", so it
        skips the benchmark ranking and bands and keeps the circuit/online
        gates. Returns ``(device_row, matched_tokens)`` or None when no
        peer beats `min_tokens`."""
        if self.db is None or not prefix_ids or not prefix_route_enabled():
            return None
        rows = self.db.query(
            """
            SELECT d.id, d.name, d.addr, d.tags FROM devices d
            JOIN device_models dm ON dm.device_id = d.id AND dm.available = 1
            WHERE d.online = 1 AND dm.model_id = ?
            """,
            (model,),
        )
        hash_memo: dict[int, list] = {}
        best: tuple[dict[str, Any], int] | None = None
        for r in rows:
            if r["id"] == exclude_device or not r["addr"]:
                continue
            if not self.circuit.allow(r["id"]):
                continue
            tags = Database.from_json(r["tags"], {})
            digest = device_prefix_digest(tags)
            if digest is None:
                continue
            bt = int(digest.get("bt", 0) or 0)
            if bt <= 0:
                continue
            if bt not in hash_memo:
                hash_memo[bt] = request_hashes_for(digest, prefix_ids)
            matched, _ = match_digest(digest, hash_memo[bt])
            if matched >= max(1, min_tokens) and (best is None or matched > best[1]):
                r["tags"] = tags
                best = (r, matched)
        return best

    # -- main entry --------------------------------------------------------

    def route(
        self,
        *,
        kind: str = "generate",
        model: str = "",
        prompt: str = "",
        provider: str = "auto",
        quality: str = "",
        thinking: bool | None = None,
        max_latency_ms: float = 0.0,
        force_cloud: bool = False,
        prefer_local: bool = True,
        prefix_ids: list[int] | None = None,
    ) -> RouteDecision:
        """Route one LLM request. The cascade mirrors RouteLLM
        (router.go:126-274); a `quality` value engages smart routing
        (router.go:407-528). The decision is recorded as a `route` span:
        chosen provider/device/tier, the human reason, the fallback chain
        actually walked, and the chosen device's circuit-breaker state.
        `prefix_ids` (prompt token ids, when the caller tokenized already)
        engages prefix-locality ranking in select_device."""
        chain: list[str] = []
        with tracing.get_tracer().span(
            "route", attrs={"kind": kind, "model": model, "quality": quality}
        ) as sp:
            d = self._route_cascade(
                chain,
                kind=kind,
                model=model,
                prompt=prompt,
                provider=provider,
                quality=quality,
                thinking=thinking,
                max_latency_ms=max_latency_ms,
                force_cloud=force_cloud,
                prefer_local=prefer_local,
                prefix_ids=prefix_ids,
            )
            sp.set_attrs(
                {
                    "provider": d.provider,
                    "decided_model": d.model,
                    "device": d.device_id,
                    "tier": d.tier,
                    "reason": d.reason,
                    "fallback_chain": ">".join(chain),
                }
            )
            if "prefix_matched_tokens" in d.extras:
                sp.set_attr(
                    "prefix_matched_tokens", d.extras["prefix_matched_tokens"]
                )
            if d.device_id:
                sp.set_attr("circuit", self.circuit.status(d.device_id))
            return d

    def _route_cascade(
        self,
        chain: list[str],
        *,
        kind: str,
        model: str,
        prompt: str,
        provider: str,
        quality: str,
        thinking: bool | None,
        max_latency_ms: float,
        force_cloud: bool,
        prefer_local: bool,
        prefix_ids: list[int] | None = None,
    ) -> RouteDecision:
        if quality:
            chain.append(f"smart:{quality}")
            return self._route_smart(
                kind=kind,
                prompt=prompt,
                quality=quality,
                thinking=thinking,
                force_cloud=force_cloud,
            )

        # explicit provider
        if provider in (PROVIDER_OPENROUTER, PROVIDER_OPENAI):
            chain.append(f"explicit:{provider}")
            return self._cloud_decision(provider, model, kind, reason="explicit provider")
        if provider == PROVIDER_TPU:
            local = self._local_decision(model, kind, max_latency_ms, prefix_ids)
            chain.append("explicit:tpu" if local else "explicit:tpu:miss")
            if local:
                return local
            return RouteDecision(
                provider=PROVIDER_TPU, kind=kind, model=model,
                reason="explicit tpu provider; no device available",
            )

        # auto cascade
        if kind == "embed" and not force_cloud:
            local = self._local_decision(model, kind, max_latency_ms, prefix_ids)
            if local:
                chain.append("local-embed")
                return local
            chain.append("local-embed:miss")
        if force_cloud:
            cloud = self._first_cloud(model, kind, reason="force_cloud")
            if cloud:
                chain.append("cloud:forced")
                return cloud
            chain.append("cloud:forced:miss")
        if prefer_local and not force_cloud:
            local = self._local_decision(model, kind, max_latency_ms, prefix_ids)
            if local:
                chain.append("local")
                return local
            chain.append("local:miss")
        cloud = self._first_cloud(model, kind, reason="cloud fallback")
        if cloud:
            chain.append("cloud")
            return cloud
        chain.append("cloud:miss")
        local = self._local_decision(model, kind, max_latency_ms, prefix_ids)
        if local:
            chain.append("local-last-resort")
            return local
        chain.append("none")
        return RouteDecision(
            provider=PROVIDER_TPU, kind=kind, model=model, reason="no provider available"
        )

    def _local_decision(
        self,
        model: str,
        kind: str,
        max_latency_ms: float,
        prefix_ids: list[int] | None = None,
    ) -> RouteDecision | None:
        if not model:
            return None
        task = "embed" if kind == "embed" else "generate"
        dev = self.select_device(
            model, task, max_latency_ms=max_latency_ms, prefix_ids=prefix_ids
        )
        if dev is None:
            return None
        d = RouteDecision(
            provider=PROVIDER_TPU,
            kind=kind,
            model=model,
            device_id=dev["id"],
            device_addr=dev["addr"],
            reason=f"local device {dev['id']} (tps={dev['bench_tps'] or 0})",
        )
        if dev.get("prefix_matched_tokens"):
            d.extras["prefix_matched_tokens"] = int(dev["prefix_matched_tokens"])
        return d

    def _first_cloud(self, model: str, kind: str, reason: str) -> RouteDecision | None:
        if self.has_openrouter:
            return self._cloud_decision(PROVIDER_OPENROUTER, model, kind, reason)
        if self.has_openai:
            return self._cloud_decision(PROVIDER_OPENAI, model, kind, reason)
        return None

    def _cloud_decision(
        self, provider: str, model: str, kind: str, reason: str
    ) -> RouteDecision:
        d = RouteDecision(provider=provider, kind=kind, model=model, reason=reason)
        if self.catalog and model:
            pricing = self.catalog.get_pricing(model)
            if pricing:
                d.extras["_price_in_1m"] = pricing["input_per_1m"]
                d.extras["_price_out_1m"] = pricing["output_per_1m"]
        return d

    # -- smart quality routing --------------------------------------------

    def _route_smart(
        self,
        *,
        kind: str,
        prompt: str,
        quality: str,
        thinking: bool | None,
        force_cloud: bool,
    ) -> RouteDecision:
        quality = quality if quality in QUALITY_TIERS else "standard"
        tokens = estimate_tokens(prompt)
        bucket = context_bucket(tokens)
        tiers = QUALITY_TIERS[quality][bucket]

        if not force_cloud:
            local = self._find_local_model(tiers, kind, thinking)
            if local:
                local.tier = local.tier or tiers[0]
                local.reason += f" (quality={quality} bucket={bucket})"
                return local

        cloud = self._find_cloud_model(CLOUD_FALLBACK_TIERS[quality], kind, thinking)
        if cloud:
            cloud.reason += f" (quality={quality} bucket={bucket})"
            return cloud

        # last resort: any local model of any tier
        local = self._find_local_model(list(TIER_ORDER), kind, thinking)
        if local:
            local.reason += f" (quality={quality} bucket={bucket}, degraded)"
            return local
        return RouteDecision(
            provider=PROVIDER_TPU, kind=kind,
            reason=f"no model for quality={quality} bucket={bucket}",
        )

    def _find_local_model(
        self, tiers: list[str], kind: str, thinking: bool | None
    ) -> RouteDecision | None:
        """Local (model, device) in the given tiers, thinking-preferring,
        load-balanced by live running+queued jobs per device
        (router.go:531-579)."""
        if self.db is None:
            return None
        marks = ",".join("?" * len(tiers))
        mkind = "embed" if kind == "embed" else "llm"
        rows = self.db.query(
            f"""
            SELECT m.id AS model_id, m.tier, m.thinking, m.context_k,
                   d.id AS device_id, d.addr,
                   (SELECT COUNT(*) FROM jobs j WHERE j.device_id = d.id
                    AND j.status IN ('queued','running')) AS live_jobs
            FROM models m
            JOIN device_models dm ON dm.model_id = m.id AND dm.available = 1
            JOIN devices d ON d.id = dm.device_id AND d.online = 1
            WHERE m.kind = ? AND m.tier IN ({marks})
            ORDER BY live_jobs ASC, m.params_b DESC
            """,
            [mkind, *tiers],
        )
        if not rows:
            return None
        # thinking preference: stable partition, preferred first
        if thinking is not None:
            rows.sort(key=lambda r: 0 if bool(r["thinking"]) == thinking else 1)
        # zoo residency (applied last = outermost key): resident models
        # first, swappable second, models the zoo does not manage last —
        # a request resolves to a model already in HBM when one fits its
        # tier, and only pays a swap when none does. Stable partition, so
        # within a band the thinking and SQL load/size order still
        # decide. No zoo attached ⇒ no sort at all ⇒ candidate order is
        # byte-identical to the pre-zoo router.
        if self.zoo is not None:
            rows.sort(key=lambda r: self.zoo.residency_band(r["model_id"]))
        for r in rows:
            dev_id = r["device_id"]
            if not self.circuit.allow(dev_id):
                continue
            if self.limits is not None:
                ok, _ = self.limits.model_allowed(dev_id, r["model_id"], r["context_k"])
                if not ok:
                    continue
            d = RouteDecision(
                provider=PROVIDER_TPU,
                kind=kind,
                model=r["model_id"],
                device_id=dev_id,
                device_addr=r["addr"],
                tier=r["tier"],
                thinking=bool(r["thinking"]),
                reason=f"local {r['model_id']} on {dev_id} load={r['live_jobs']}",
            )
            return d
        return None

    def _find_cloud_model(
        self, tiers: list[str], kind: str, thinking: bool | None
    ) -> RouteDecision | None:
        """Cloud model from the catalog in the given tiers, widest context
        first (router.go:582-616), with pricing injected into the payload."""
        if self.db is None or not (self.has_openrouter or self.has_openai):
            return None
        marks = ",".join("?" * len(tiers))
        mkind = "embed" if kind == "embed" else "llm"
        rows = self.db.query(
            f"""
            SELECT m.id AS model_id, m.tier, m.thinking, m.context_k,
                   p.input_per_1m, p.output_per_1m
            FROM models m
            JOIN model_pricing p ON p.model_id = m.id
            WHERE m.kind = ? AND m.tier IN ({marks}) AND m.id LIKE '%/%'
            ORDER BY m.context_k DESC, p.output_per_1m ASC
            """,
            [mkind, *tiers],
        )
        if not rows:
            return None
        if thinking is not None:
            rows.sort(key=lambda r: 0 if bool(r["thinking"]) == thinking else 1)
        r = rows[0]
        provider = PROVIDER_OPENROUTER if self.has_openrouter else PROVIDER_OPENAI
        return RouteDecision(
            provider=provider,
            kind=kind,
            model=r["model_id"],
            tier=r["tier"],
            thinking=bool(r["thinking"]),
            reason=f"cloud {r['model_id']}",
            extras={
                "_price_in_1m": r["input_per_1m"],
                "_price_out_1m": r["output_per_1m"],
            },
        )
