"""Per-device circuit breaker.

Parity with the reference's in-memory breaker (`core/internal/routing/
router.go:22-89`): 3 consecutive failures degrade a device for 5 minutes;
after the window one probe request is allowed through; any success resets.
Status surfaces as ok / degraded / probe on the dashboard (`router.go:78-89`).

TPU adaptation: "device failure" here includes executor-reported conditions
(XLA OOM, mesh member loss) reported via `record(device, ok=False)` by the
serving layer, not just HTTP connection errors.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


DEGRADE_AFTER_FAILURES = 3
DEGRADE_WINDOW_S = 300.0


class CircuitStatus:
    OK = "ok"
    DEGRADED = "degraded"
    PROBE = "probe"


@dataclass
class _State:
    failures: int = 0
    degraded_at: float = 0.0
    probe_inflight: bool = False


class CircuitBreaker:
    def __init__(
        self,
        threshold: int = DEGRADE_AFTER_FAILURES,
        window_s: float = DEGRADE_WINDOW_S,
    ):
        self.threshold = threshold
        self.window_s = window_s
        self._lock = threading.Lock()
        self._by_device: dict[str, _State] = {}

    def record(self, device_id: str, ok: bool) -> None:
        """Record a request outcome for a device."""
        if not device_id:
            return
        with self._lock:
            st = self._by_device.setdefault(device_id, _State())
            if ok:
                st.failures = 0
                st.degraded_at = 0.0
                st.probe_inflight = False
            else:
                st.failures += 1
                st.probe_inflight = False
                if st.failures >= self.threshold and st.degraded_at == 0.0:
                    st.degraded_at = time.time()
                elif st.degraded_at != 0.0:
                    # failed probe → restart the degrade window
                    st.degraded_at = time.time()

    def allow(self, device_id: str) -> bool:
        """True if a request may be routed to the device. After the degrade
        window expires, exactly one probe is let through until its outcome
        is recorded."""
        if not device_id:
            return True
        with self._lock:
            st = self._by_device.get(device_id)
            if st is None or st.degraded_at == 0.0:
                return True
            if time.time() - st.degraded_at < self.window_s:
                return False
            if st.probe_inflight:
                return False
            st.probe_inflight = True
            return True

    def status(self, device_id: str) -> str:
        with self._lock:
            st = self._by_device.get(device_id)
            if st is None or st.degraded_at == 0.0:
                return CircuitStatus.OK
            if time.time() - st.degraded_at < self.window_s:
                return CircuitStatus.DEGRADED
            return CircuitStatus.PROBE

    def snapshot(self) -> dict[str, dict]:
        """Dashboard view: device → {failures, status}."""
        out = {}
        with self._lock:
            items = list(self._by_device.items())
        for dev, st in items:
            out[dev] = {"failures": st.failures, "status": self.status(dev)}
        return out

    # test hook mirroring the reference's direct DegradedAt rewind
    # (`router_test.go:195-212`)
    def _rewind_degraded_at(self, device_id: str, seconds: float) -> None:
        with self._lock:
            st = self._by_device.get(device_id)
            if st and st.degraded_at:
                st.degraded_at -= seconds
