"""Device capability limits: HBM-aware derivation + allow/deny policy.

Parity with the reference's RAM→params derivation and ModelAllowed gate
(`core/internal/limits/limits.go:84-247`), re-derived for TPU devices:

  - The reference sizes Ollama boxes by host RAM/VRAM
    (≤8GB→5B params, ≤16GB→12B, else 0.75·mem as GB of weights).
  - TPU devices are sized by per-chip HBM × chip count: bf16 weights take
    2 bytes/param, and serving needs headroom for the KV cache, activations
    and XLA workspace, so usable weight budget ≈ 50% of total HBM. A v5e
    chip (16 GB HBM) thus carries ≤4B params solo and Llama-3.1-8B needs
    tp≥2; a v5e-8 slice (128 GB) carries ≤32B.
  - `max_context_k` derives from the HBM left after weights at the device's
    largest resident model, assuming GQA KV of ~128 KB/token (8B-class).

Spec sources mirror the reference: `DEVICE_LIMITS_JSON` / `DEVICE_LIMITS_FILE`
env (a JSON object keyed by device id, `"*"` for the default), preset entries
are never overwritten by derivation (`limits.go:83-102` semantics), and
STRICT mode denies models with unknown size.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any

from ..state.catalog import Catalog
from ..state.db import Database

KV_BYTES_PER_TOKEN_8B = 128 * 1024  # GQA 8 KV heads × 128 dim × 2 × bf16 × 32 layers


@dataclass
class DeviceLimitSpec:
    max_params_b: float = 0.0
    max_size_gb: float = 0.0
    max_context_k: int = 0
    allow_models: list[str] = field(default_factory=list)
    deny_models: list[str] = field(default_factory=list)
    source: str = "derived"  # derived | preset

    def to_row(self) -> dict[str, Any]:
        return {
            "max_params_b": self.max_params_b,
            "max_size_gb": self.max_size_gb,
            "max_context_k": self.max_context_k,
            "allow_models": self.allow_models,
            "deny_models": self.deny_models,
            "source": self.source,
        }


def tags_fresh(tags: dict | None, now: float | None = None) -> bool:
    """Whether a device's advertised tags are recent enough to trust.

    Devices stamp ``tags_at`` (epoch seconds) on every discovery refresh
    (server.register_local_device); a wedged engine stops refreshing but
    its *last* advertised headroom/digest would keep attracting traffic
    forever — the stale-tag routing hazard. Tags older than
    ``ROUTE_TAG_TTL_S`` (default 180 s = three missed discovery refreshes
    at the default DISCOVERY_INTERVAL of 60 s) read as stale; devices
    that never stamp (older executors, test fixtures) read as fresh so
    the TTL only bites on opted-in devices. `now` is injectable for
    frozen-clock tests."""
    ts = (tags or {}).get("tags_at")
    if ts is None:
        return True
    try:
        ttl = float(os.environ.get("ROUTE_TAG_TTL_S", "180") or 0.0)
    except ValueError:
        ttl = 180.0
    if ttl <= 0:
        return True
    import time as _time

    now = _time.time() if now is None else now
    try:
        return (now - float(ts)) <= ttl
    except (TypeError, ValueError):
        return True


def device_headroom(tags: dict | None, now: float | None = None) -> float:
    """Shed-free KV-pool headroom a device advertises in its `kv_headroom`
    tag (server.register_local_device), in [0, 1]. Devices without the tag
    (no pool, older executors) read as 1.0 — fully admittable — so the
    router's saturation de-ranking only ever acts on devices that opted in.
    Stale tags (tags_fresh False) read as 0.0: a device that stopped
    refreshing is de-ranked to the saturated band rather than trusted at
    its last-known headroom."""
    if not tags_fresh(tags, now):
        return 0.0
    try:
        return float((tags or {}).get("kv_headroom", 1.0))
    except (TypeError, ValueError):
        return 1.0


def device_migration(tags: dict | None) -> bool:
    """Whether the device advertises KV migration (the `migration` tag,
    server.register_local_device with TPU_MIGRATE on). A saturated device
    that can drain its pool to a peer recovers faster than one that can
    only shed, so the router prefers it within the saturated band."""
    return bool((tags or {}).get("migration", False))


def device_warming(tags: dict | None) -> bool:
    """Whether the device is still compiling its executable zoo (the
    `warming` tag: any local engine's warmup readiness below fully_warm —
    server.register_local_device). A warming device SERVES — its critical
    first-token prefix compiled synchronously at boot — but a never-seen
    shape can still eat a cold XLA compile, so the router ranks it behind
    fully-warm healthy peers instead of letting fresh traffic discover
    the remaining cold shapes the hard way. Devices without the tag
    (pre-warmup executors, warmup off) read as not warming."""
    return bool((tags or {}).get("warming", False))


def device_prefix_digest(tags: dict | None, now: float | None = None) -> dict | None:
    """The device's advertised prefix-chain digest (routing/prefix.py
    build_digest shape), or None when absent or stale — a stale digest
    describes chains the engine may long since have evicted, so the
    router must not score on it."""
    if not tags_fresh(tags, now):
        return None
    d = (tags or {}).get("prefix_digest")
    return d if isinstance(d, dict) else None


def device_queue_depth(tags: dict | None) -> float:
    """Admission-queue depth the device last advertised (`queue_depth`
    tag) — the congestion side of the prefix-locality score."""
    try:
        return max(0.0, float((tags or {}).get("queue_depth", 0.0)))
    except (TypeError, ValueError):
        return 0.0


def device_prefill_cost(tags: dict | None) -> float:
    """Measured prefill cost in seconds/token (`prefill_us_per_tok` tag,
    from the perf observatory's prefill-family phase walls). 0.0 when the
    device hasn't measured yet — the router then falls back to a
    conservative default so digests still rank."""
    try:
        return max(0.0, float((tags or {}).get("prefill_us_per_tok", 0.0))) / 1e6
    except (TypeError, ValueError):
        return 0.0


def derive_device_limits(hbm_gb: float, chips: int = 1) -> DeviceLimitSpec:
    """HBM budget → capability caps for a TPU device (slice).

    Usable weight budget = 50% of total HBM (bf16 weights; rest is KV cache,
    activations, XLA workspace). Context cap assumes the largest co-resident
    model leaves ~25% of HBM for KV at ~128KB/token (8B-class GQA).
    """
    total = max(hbm_gb, 0.0) * max(chips, 1)
    weight_budget_gb = total * 0.5
    max_params_b = weight_budget_gb / 2.0  # bf16: 2 GB per B params
    kv_budget_bytes = total * 0.25 * (1 << 30)
    max_context = int(kv_budget_bytes / KV_BYTES_PER_TOKEN_8B)
    # round context down to a power-of-two-ish K bucket
    max_context_k = 1
    while max_context_k * 2 * 1024 <= max_context:
        max_context_k *= 2
    if total <= 0:
        return DeviceLimitSpec()
    return DeviceLimitSpec(
        max_params_b=round(max_params_b, 2),
        max_size_gb=round(weight_budget_gb, 2),
        max_context_k=max_context_k,
        source="derived",
    )


def parse_limit_specs(
    limits_json: str | None = None, limits_file: str | None = None
) -> dict[str, DeviceLimitSpec]:
    """Parse `DEVICE_LIMITS_JSON` / `DEVICE_LIMITS_FILE` into specs keyed by
    device id ("*" = default applied to devices without their own entry)."""
    raw = ""
    if limits_json is None:
        limits_json = os.environ.get("DEVICE_LIMITS_JSON", "")
    if limits_file is None:
        limits_file = os.environ.get("DEVICE_LIMITS_FILE", "")
    if limits_json.strip():
        raw = limits_json
    elif limits_file.strip():
        try:
            with open(limits_file) as f:
                raw = f.read()
        except OSError:
            return {}
    if not raw.strip():
        return {}
    try:
        data = json.loads(raw)
    except json.JSONDecodeError:
        return {}
    specs: dict[str, DeviceLimitSpec] = {}
    if not isinstance(data, dict):
        return specs
    for dev, entry in data.items():
        if not isinstance(entry, dict):
            continue
        specs[dev] = DeviceLimitSpec(
            max_params_b=float(entry.get("max_params_b", 0) or 0),
            max_size_gb=float(entry.get("max_size_gb", 0) or 0),
            max_context_k=int(entry.get("max_context_k", 0) or 0),
            allow_models=[str(m) for m in entry.get("allow_models", []) or []],
            deny_models=[str(m) for m in entry.get("deny_models", []) or []],
            source="preset",
        )
    return specs


def _name_matches(model_id: str, patterns: list[str]) -> bool:
    low = model_id.lower()
    for p in patterns:
        p = p.lower().strip()
        if not p:
            continue
        if p == low or p in low:
            return True
    return False


class LimitsEngine:
    """Applies limit specs to the device_limits table and gates models.

    Mirrors the reference's apply-at-interval + ModelAllowed flow
    (`limits.go:163-247`, re-applied by the `main.go:56-67` ticker).
    """

    def __init__(self, db: Database, strict: bool | None = None):
        self.db = db
        self.catalog = Catalog(db)
        if strict is None:
            strict = os.environ.get("STRICT_MODEL_LIMITS", "") in ("1", "true", "yes")
        self.strict = strict

    # -- apply -------------------------------------------------------------

    def apply_specs(self, specs: dict[str, DeviceLimitSpec] | None = None) -> int:
        """Upsert presets for known devices; derive limits for TPU devices
        without a preset (using tags.hbm_gb/chips). Preset rows are never
        overwritten by derivation. Returns rows written."""
        if specs is None:
            specs = parse_limit_specs()
        default = specs.get("*")
        written = 0
        for dev in self.catalog.list_devices():
            dev_id = dev["id"]
            spec = specs.get(dev_id)
            if spec is None:
                existing = self.get(dev_id)
                if existing is not None and existing.source == "preset":
                    continue  # presets win over derivation
                tags = dev.get("tags") or {}
                hbm = float(tags.get("hbm_gb", 0) or 0)
                chips = int(tags.get("chips", 1) or 1)
                if hbm > 0:
                    spec = derive_device_limits(hbm, chips)
                elif default is not None:
                    spec = default
                else:
                    continue
            self._upsert(dev_id, spec)
            written += 1
        return written

    def _upsert(self, device_id: str, spec: DeviceLimitSpec) -> None:
        import time as _time

        self.db.execute(
            "INSERT INTO device_limits(device_id, max_params_b, max_size_gb,"
            " max_context_k, allow_models, deny_models, source, updated_at)"
            " VALUES(?,?,?,?,?,?,?,?) ON CONFLICT(device_id) DO UPDATE SET"
            " max_params_b=excluded.max_params_b, max_size_gb=excluded.max_size_gb,"
            " max_context_k=excluded.max_context_k, allow_models=excluded.allow_models,"
            " deny_models=excluded.deny_models, source=excluded.source,"
            " updated_at=excluded.updated_at",
            (
                device_id,
                spec.max_params_b,
                spec.max_size_gb,
                spec.max_context_k,
                Database.to_json(spec.allow_models),
                Database.to_json(spec.deny_models),
                spec.source,
                _time.time(),
            ),
        )

    def get(self, device_id: str) -> DeviceLimitSpec | None:
        row = self.db.query_one(
            "SELECT * FROM device_limits WHERE device_id=?", (device_id,)
        )
        if not row:
            return None
        return DeviceLimitSpec(
            max_params_b=row["max_params_b"],
            max_size_gb=row["max_size_gb"],
            max_context_k=row["max_context_k"],
            allow_models=Database.from_json(row["allow_models"], []),
            deny_models=Database.from_json(row["deny_models"], []),
            source=row["source"],
        )

    # -- gate --------------------------------------------------------------

    def model_allowed(
        self, device_id: str, model_id: str, context_k: int = 0
    ) -> tuple[bool, str]:
        """Gate a (device, model) pair. Returns (allowed, reason).

        Order mirrors `limits.go:163-247`: deny list → allow list → size/
        params caps (STRICT denies unknown sizes) → context cap.
        """
        spec = self.get(device_id)
        if spec is None:
            return True, "no limits"
        if _name_matches(model_id, spec.deny_models):
            return False, "denied by deny_models"
        if spec.allow_models and not _name_matches(model_id, spec.allow_models):
            return False, "not in allow_models"
        model = self.catalog.get_model(model_id)
        params_b = float(model["params_b"]) if model else 0.0
        size_gb = float(model["size_gb"]) if model else 0.0
        if params_b <= 0 and size_gb <= 0:
            if self.strict:
                return False, "unknown model size (strict)"
        if spec.max_params_b > 0 and params_b > spec.max_params_b:
            return False, f"params {params_b}B > cap {spec.max_params_b}B"
        if spec.max_size_gb > 0 and size_gb > spec.max_size_gb:
            return False, f"size {size_gb}GB > cap {spec.max_size_gb}GB"
        if spec.max_context_k > 0 and context_k > spec.max_context_k:
            return False, f"context {context_k}K > cap {spec.max_context_k}K"
        return True, "ok"
