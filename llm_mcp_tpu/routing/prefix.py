"""Prefix-chain fingerprinting shared by engines and the router.

The paged ledger (executor/paging.py) keys a resident prefix entry on the
literal tuple of its token ids; the engine's prompt-prefix cache stores
pow2-floored lengths of those tuples. To make the *fleet* cache-aware the
router needs to compare a request's prompt against every peer's resident
chains without shipping token ids around, so both sides hash the same
thing the ledger keys: the block-aligned prefix chain, as a rolling
blake2b over block-sized runs of token ids (block size =
``TPU_KV_BLOCK_TOKENS``, the ledger's own unit). Because the hash at
boundary ``j`` commits to exactly ``ids[:j*bt]``, equal hashes mean equal
chains — the router never needs the ids back.

An engine advertises a **digest** of its resident chains through the
discovery tag channel (next to ``kv_headroom``):

- ``heads``: the top-K chains by stored length, as ``{chain_hash: tokens}``
  — an exact-match table for the common case (agent/system prompts shared
  by most traffic);
- ``bloom``: a small bloom filter over *every* boundary hash of every
  resident chain — catches partial matches (the peer holds a longer or
  shorter chain sharing our leading blocks) that fell out of the top-K.

``match_digest`` walks the request's boundary hashes longest-first: a
head hit is exact; a bloom hit is probabilistic (a false positive costs
one mispriced routing score, never correctness — admission re-checks the
real tuples). Everything here is stdlib-only so the router side stays
import-light.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Any, Iterable

# Digest sizing: 16 hex chars (64 bits) per chain hash keeps tag JSON
# small while making accidental collisions across a fleet's worth of
# chains (~thousands) negligible. The bloom is 512 bits / 4 probes by
# default: ~1% false-positive rate at ~50 boundary hashes per engine.
HASH_HEX = 16
DEFAULT_TOP_K = 8
DEFAULT_BLOOM_BITS = 512
DEFAULT_BLOOM_HASHES = 4
DIGEST_VERSION = 1


def prefix_route_enabled() -> bool:
    """``TPU_PREFIX_ROUTE=0`` is a true no-op: no hashing, no digest
    matching, no re-ranking — the router reproduces today's decisions
    byte-for-byte. Default on (scoring is inert until peers advertise
    digests, so the default costs nothing on single-engine fleets)."""
    return os.environ.get("TPU_PREFIX_ROUTE", "1") not in ("0", "false", "no")


def fetch_min_tokens() -> int:
    """Crossover length below which recomputing a prefix locally beats
    fetching its KV from a peer (``TPU_PREFIX_FETCH_MIN_TOKENS``). The
    default is measured by bench.py's prefix-tier microbench (fetch decode
    + device upload vs chunked prefill): on CPU-backed test engines the
    crossover sits near one 256-token chunk, and real TPU prefill is
    faster still — below ~256 tokens the wire round-trip always loses."""
    try:
        return int(os.environ.get("TPU_PREFIX_FETCH_MIN_TOKENS", "256"))
    except ValueError:
        return 256


def chain_hashes(ids: Iterable[int], block_tokens: int) -> list[tuple[int, str]]:
    """Rolling hash of a token chain at every ledger-block boundary, plus
    the (possibly unaligned) chain head.

    Returns ascending ``[(n_tokens, hash16), ...]`` where ``hash16``
    commits to exactly ``ids[:n_tokens]``: ``h_j = blake2b(h_{j-1} ||
    pack(ids[(j-1)*bt : j*bt]))``. The final element always covers the
    full chain, so a stored entry's *head hash* is ``chain_hashes(key,
    bt)[-1][1]`` — computed identically by the request side."""
    toks = list(ids)
    bt = max(1, int(block_tokens))
    out: list[tuple[int, str]] = []
    h = b""
    for start in range(0, len(toks), bt):
        run = toks[start : start + bt]
        d = hashlib.blake2b(digest_size=HASH_HEX // 2)
        d.update(h)
        d.update(struct.pack(f"<{len(run)}q", *run))
        h = d.digest()
        out.append((start + len(run), h.hex()))
    return out


def _bloom_bits(hash16: str, mbits: int, nh: int) -> list[int]:
    """Derive `nh` bloom probe positions from one 64-bit chain hash
    (split halves, double hashing — Kirsch-Mitzenmacher)."""
    v = int(hash16, 16)
    lo, hi = v & 0xFFFFFFFF, v >> 32
    return [(lo + i * hi) % mbits for i in range(nh)]


def build_digest(
    chains: Iterable[tuple[Iterable[int], int]],
    block_tokens: int,
    *,
    top_k: int = DEFAULT_TOP_K,
    mbits: int = DEFAULT_BLOOM_BITS,
    nh: int = DEFAULT_BLOOM_HASHES,
) -> dict[str, Any]:
    """Digest of an engine's resident prefix chains for the discovery tag
    channel. `chains` is ``[(token_ids, n_tokens), ...]`` — the ledger /
    prefix-cache snapshot (`engine.prefix_chains()`). JSON-serializable
    and compact: K head entries plus mbits/4 hex chars."""
    heads: dict[str, int] = {}
    bloom = bytearray(mbits // 8)
    ranked = sorted(chains, key=lambda c: -int(c[1]))
    for rank, (ids, n_tokens) in enumerate(ranked):
        bounds = chain_hashes(ids, block_tokens)
        if not bounds:
            continue
        if rank < top_k:
            heads[bounds[-1][1]] = int(n_tokens)
        for _, h in bounds:
            for bit in _bloom_bits(h, mbits, nh):
                bloom[bit // 8] |= 1 << (bit % 8)
    return {
        "v": DIGEST_VERSION,
        "bt": int(block_tokens),
        "heads": heads,
        "bloom": bytes(bloom).hex(),
        "mbits": mbits,
        "nh": nh,
    }


def merge_digests(digests: list[dict[str, Any]], top_k: int = DEFAULT_TOP_K) -> dict[str, Any] | None:
    """Union per-engine digests into one device tag (pooled engines).
    Blooms OR together when sized alike; heads keep the top-K longest."""
    digests = [d for d in digests if d and d.get("v") == DIGEST_VERSION]
    if not digests:
        return None
    if len(digests) == 1:
        return digests[0]
    base = digests[0]
    heads: dict[str, int] = {}
    bloom = bytearray(int(base["mbits"]) // 8)
    for d in digests:
        if int(d["mbits"]) != int(base["mbits"]) or int(d["bt"]) != int(base["bt"]):
            continue  # mismatched geometry never merges; first engine wins
        for h, n in d.get("heads", {}).items():
            heads[h] = max(int(n), heads.get(h, 0))
        raw = bytes.fromhex(d.get("bloom", ""))
        for i, b in enumerate(raw[: len(bloom)]):
            bloom[i] |= b
    top = dict(sorted(heads.items(), key=lambda kv: -kv[1])[:top_k])
    return {
        "v": DIGEST_VERSION,
        "bt": int(base["bt"]),
        "heads": top,
        "bloom": bytes(bloom).hex(),
        "mbits": int(base["mbits"]),
        "nh": int(base["nh"]),
    }


def match_digest(
    digest: dict[str, Any] | None,
    request_hashes: list[tuple[int, str]],
) -> tuple[int, bool]:
    """Longest resident-prefix match a peer's digest claims for a request.

    `request_hashes` is ``chain_hashes(prompt_ids, bt)`` computed by the
    caller with the digest's own ``bt`` (geometry mismatch → no match).
    Returns ``(matched_tokens, exact)``: a head hit is exact (the peer
    stores that very chain, length = the boundary we hashed); a bloom hit
    means the peer holds *some* chain through that boundary (possibly a
    false positive, which only misprices one score). Scanned longest-first
    so the first hit is the best claim."""
    if not digest or digest.get("v") != DIGEST_VERSION or not request_hashes:
        return 0, False
    heads = digest.get("heads") or {}
    try:
        bloom = bytes.fromhex(digest.get("bloom", ""))
        mbits = int(digest.get("mbits", 0))
        nh = int(digest.get("nh", 0))
    except (ValueError, TypeError):
        bloom, mbits, nh = b"", 0, 0
    for n_tokens, h in reversed(request_hashes):
        if h in heads:
            return n_tokens, True
        if mbits and nh and len(bloom) * 8 >= mbits:
            if all(bloom[b // 8] >> (b % 8) & 1 for b in _bloom_bits(h, mbits, nh)):
                return n_tokens, False
    return 0, False


def request_hashes_for(digest: dict[str, Any] | None, ids: list[int]) -> list[tuple[int, str]]:
    """Boundary hashes of a request's prompt in a digest's own geometry,
    dropping the head boundary when it covers the *whole* prompt — a hit
    must leave >= 1 suffix token (the engine cache's strict-prefix rule),
    so claiming the full prompt would promise savings admission can't
    deliver."""
    if not digest:
        return []
    bounds = chain_hashes(ids, int(digest.get("bt", 0) or 0))
    return [(n, h) for n, h in bounds if n < len(ids)]
