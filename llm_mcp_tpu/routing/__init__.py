from .circuit import CircuitBreaker, CircuitStatus
from .limits import DeviceLimitSpec, LimitsEngine, derive_device_limits
from .router import Router, RouteDecision, estimate_tokens, context_bucket, quality_deadline_s

__all__ = [
    "CircuitBreaker",
    "CircuitStatus",
    "DeviceLimitSpec",
    "LimitsEngine",
    "derive_device_limits",
    "Router",
    "RouteDecision",
    "estimate_tokens",
    "context_bucket",
    "quality_deadline_s",
]
