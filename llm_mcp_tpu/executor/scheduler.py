"""Token-budget prefill/decode scheduler (stall-free continuous batching).

The Sarathi-Serve / vLLM insight: schedule prefill by *token budget inside
the decode round*, not by host wall-clock alternation. The engine loop asks
`decide()` once per iteration for a prefill token budget, stages that many
prompt tokens from mid-prefill slots, and fuses them into the same device
dispatch as the decode round — decode cadence never stalls behind a prefill
backlog, and TTFT is bounded by budget arithmetic instead of an
environment-tuned multiplier (the retired `TPU_PREFILL_BOOST`, whose
wall-clock budget let prefill monopolize the loop on a locally-attached
chip: 2428 → 464.7 tok/s serve, prefill 81–93% of window wall).

Policy, per round with active decode slots:

  fair_cap = decode_round_s / prefill_tok_s
      The prefill token count whose device time ≈ one decode round, so a
      fused round costs at most ~2× a pure decode round — in-flight streams'
      inter-token latency stays within 2× their no-backlog cadence.
  need = backlog_tokens / rounds_until_deadline
      The drain rate that activates the OLDEST mid-prefill prompt within
      `target_ttft_ms` of its arrival.
  budget = clamp(need, min_budget, fair_cap)
      `need > fair_cap` means the deadline is unreachable without starving
      decode; the starvation counter records it (telemetry: raise
      target_ttft_ms, add capacity, or shed load).

With ZERO active decode slots (pure-prefill window — e.g. a cold burst of
long prompts) there is no cadence to protect: the budget is the whole
backlog and chunks run back-to-back.

Both cost terms self-tune from measured dispatches (EMAs): decode-round
seconds from prefill-free rounds, per-token prefill seconds from standalone
chunk dispatches and from the fused rounds' time over the decode EMA. The
same object drives `GenerationEngine` and the multi-host `SliceEngine`
leader (followers replay dispatches and need no policy).
"""

from __future__ import annotations

import math
import time

from ..telemetry.recorder import get_recorder

__all__ = ["TokenBudgetScheduler", "parse_tenant_quotas"]

_EMA = 0.7  # keep-fraction; matches the engine's old decode-time smoothing

# Per-tenant quota burst window: a tenant's token bucket holds this many
# seconds of its rate, so short bursts ride through while sustained
# overload throttles within a couple of windows.
TENANT_BURST_S = 2.0


def parse_tenant_quotas(spec: str) -> dict[str, float]:
    """`TPU_TENANT_QUOTAS="alice=600,bob=300"` -> {"alice": 600.0, ...}.

    Values are tokens/second. A `*` key sets the default for tenants not
    named explicitly; tenants with no quota (and the empty tenant id) are
    unmetered. Malformed entries are dropped rather than raised — a typo'd
    quota must not take the serve path down."""
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            rate = float(val)
        except ValueError:
            continue
        if name.strip() and rate > 0:
            out[name.strip()] = rate
    return out


class TokenBudgetScheduler:
    def __init__(
        self,
        *,
        target_ttft_ms: float = 2000.0,
        min_budget: int = 64,
        decode_seed_s: float = 0.05,
        prefill_tok_seed_s: float = 1e-4,
        tenant_quotas: dict[str, float] | None = None,
    ):
        self.target_ttft_s = max(1.0, float(target_ttft_ms)) / 1000.0
        # floor: a chunk dispatch costs ~a weight pass regardless of size, so
        # sub-floor budgets would pay full dispatch overhead per few tokens
        self.min_budget = max(1, int(min_budget))
        # EMA seeds — replaced by measurements after the first observed
        # dispatches; the seeds only shape the first few cold rounds
        self.decode_round_s = float(decode_seed_s)
        self.prefill_tok_s = float(prefill_tok_seed_s)
        self.last_budget = 0
        self.starved_rounds = 0
        self.verify_rounds = 0
        self.verify_tokens = 0
        # Pad-waste accounting (ragged-prefill line of record): dispatches
        # report both their TRUE token count and the DISPATCHED shape
        # (rows × bucket for the padded path, packed T for ragged). The
        # per-token cost EMA divides by the dispatched count — compute
        # scales with pads, and attributing pad time to true tokens
        # inflated the EMA and shrank fair_cap under mixed fill (the
        # pre-ragged bug this fixes). The cumulative totals feed the
        # prefill_pad_waste_pct stat bench promotes to the line of record.
        self.prefill_true_tokens = 0
        self.prefill_padded_tokens = 0
        self.pad_waste = 0.0  # EMA of per-dispatch waste fraction
        # Per-tenant quotas (model zoo tenancy): tokens/second per tenant,
        # enforced as token buckets holding TENANT_BURST_S of rate. The
        # EMA-costed budget machinery above stays global — quotas act at
        # ADMISSION (tenant_admit -> per-tenant 429), so an over-quota
        # tenant sheds at the door instead of starving in-flight streams.
        # Empty dict ⇒ every tenant unmetered ⇒ zero behavior change.
        self.tenant_quotas = {
            k: float(v) for k, v in (tenant_quotas or {}).items()
            if float(v) > 0
        }
        self._tenant_level: dict[str, float] = {}  # bucket fill, tokens
        self._tenant_ts: dict[str, float] = {}     # last refill stamp
        self.tenant_throttled: dict[str, int] = {}  # tenant -> 429 count
        self.tenant_charged: dict[str, int] = {}    # tenant -> tokens billed

    # -- cost observation --------------------------------------------------

    def observe_decode(self, round_s: float) -> None:
        """A prefill-free decode round's wall time (dispatch → fetch)."""
        if round_s > 0:
            self.decode_round_s = _EMA * self.decode_round_s + (1 - _EMA) * round_s

    def observe_prefill(
        self, tokens: int, seconds: float, padded_tokens: int = 0
    ) -> None:
        """A standalone chunk dispatch: `tokens` TRUE prompt tokens in
        `seconds`. `padded_tokens` is the dispatched token shape (≥ tokens;
        0 ⇒ unknown, treated as un-padded): the cost EMA divides by it —
        the device computed every pad — while the waste ratio records how
        much of the dispatch was pads."""
        if tokens <= 0 or seconds <= 0:
            return
        comp = max(int(tokens), int(padded_tokens))
        per = min(1.0, max(1e-8, seconds / comp))
        self.prefill_tok_s = _EMA * self.prefill_tok_s + (1 - _EMA) * per
        self.prefill_true_tokens += int(tokens)
        self.prefill_padded_tokens += comp
        waste = 1.0 - tokens / comp
        self.pad_waste = _EMA * self.pad_waste + (1 - _EMA) * waste

    def observe_fused(
        self, round_s: float, prefill_tokens: int, padded_tokens: int = 0
    ) -> None:
        """A fused round: attribute the time over the decode EMA to its
        prefill tokens. Rounds faster than the EMA teach nothing (the
        residual would be negative)."""
        extra = round_s - self.decode_round_s
        if prefill_tokens > 0 and extra > 0:
            self.observe_prefill(
                prefill_tokens, extra, padded_tokens=padded_tokens
            )

    def observe_verify(self, tokens: int, seconds: float) -> None:
        """A speculative verify dispatch: `tokens` chunk positions (the base
        token plus drafts, summed over slots) in `seconds`. Verify rides the
        same chunked-prefill machinery as prompt chunks, so its per-token
        cost feeds the same EMA the budget arithmetic runs on."""
        self.verify_rounds += 1
        self.verify_tokens += max(0, int(tokens))
        self.observe_prefill(tokens, seconds)

    # -- policy ------------------------------------------------------------

    def fair_cap(self) -> int:
        """Prefill tokens whose estimated device time ≈ one decode round.
        The budget is granted in TRUE tokens but a padded dispatch computes
        its pads too — discount by the observed waste EMA so `cap` true
        tokens of staging still land ≈ one decode round of device time
        (under ragged prefill the waste EMA ≈ 0 and the discount vanishes)."""
        cap = self.decode_round_s / self.prefill_tok_s
        cap *= max(0.0, 1.0 - self.pad_waste)
        return max(self.min_budget, int(cap))

    def decide(
        self,
        backlog_tokens: int,
        n_active: int,
        oldest_wait_s: float,
        reserved_tokens: int = 0,
    ) -> int:
        """Prefill token budget for the next engine iteration.

        backlog_tokens: prompt tokens not yet written for mid-prefill slots.
        n_active: decoding slots this round (0 ⇒ pure-prefill window).
        oldest_wait_s: age of the oldest mid-prefill request.
        reserved_tokens: chunk tokens this iteration already owes elsewhere —
            a speculative verify dispatch costs chunk positions through the
            same machinery, so they come out of the round's prefill budget
            (the budget may drop to 0; the backlog waits a round rather than
            stacking verify + a full prefill chunk on one decode cadence).
        """
        if backlog_tokens <= 0:
            self.last_budget = 0
            return 0
        if n_active == 0:
            # pure-prefill window: no decode cadence to protect — run the
            # whole backlog back-to-back (the stale-budget bug this replaces
            # paced cold bursts in arbitrary 50 ms wall-clock slices)
            self.last_budget = backlog_tokens
            get_recorder().event(
                "budget", budget=backlog_tokens, backlog=backlog_tokens,
                n_active=0, starved=False,
            )
            return backlog_tokens
        cap = self.fair_cap()
        headroom_s = max(self.target_ttft_s - oldest_wait_s, self.decode_round_s)
        rounds_left = max(1.0, headroom_s / max(self.decode_round_s, 1e-6))
        need = int(math.ceil(backlog_tokens / rounds_left))
        starved = need > cap
        if starved:
            self.starved_rounds += 1
        budget = max(self.min_budget, min(need, cap))
        if reserved_tokens > 0:
            budget = max(0, budget - int(reserved_tokens))
        self.last_budget = budget
        # flight-recorder step event (telemetry/recorder.py): the decision
        # a post-mortem needs to explain a TTFT burn or a decode stall —
        # what budget was granted against what backlog, and whether the
        # deadline was already unreachable (starved)
        get_recorder().event(
            "budget", budget=budget, backlog=backlog_tokens,
            n_active=n_active, starved=starved,
        )
        return budget

    # -- per-tenant quotas -------------------------------------------------

    def _tenant_rate(self, tenant: str) -> float:
        """Quota for `tenant` in tokens/s; 0 ⇒ unmetered. The `*` entry is
        the default for tenants with no explicit row."""
        if not tenant or not self.tenant_quotas:
            return 0.0
        return self.tenant_quotas.get(tenant, self.tenant_quotas.get("*", 0.0))

    def _refill(self, tenant: str, rate: float, now: float) -> float:
        """Advance `tenant`'s bucket to `now` and return its level."""
        burst = rate * TENANT_BURST_S
        level = self._tenant_level.get(tenant, burst)
        last = self._tenant_ts.get(tenant, now)
        level = min(burst, level + rate * max(0.0, now - last))
        self._tenant_level[tenant] = level
        self._tenant_ts[tenant] = now
        return level

    def tenant_charge(
        self, tenant: str, tokens: int, now: float | None = None
    ) -> None:
        """Bill `tokens` (prompt + generated) against `tenant`'s bucket.
        The level may go negative — a large request pushes the tenant's
        next admission out proportionally — but is floored at one burst of
        debt so a single huge request can't lock a tenant out forever."""
        rate = self._tenant_rate(tenant)
        if rate <= 0 or tokens <= 0:
            return
        now = time.monotonic() if now is None else now
        level = self._refill(tenant, rate, now)
        burst = rate * TENANT_BURST_S
        self._tenant_level[tenant] = max(-burst, level - tokens)
        self.tenant_charged[tenant] = (
            self.tenant_charged.get(tenant, 0) + int(tokens)
        )

    def tenant_admit(
        self, tenant: str, now: float | None = None
    ) -> tuple[bool, float]:
        """Quota gate for one arriving request: (admit, retry_after_s).
        Unmetered tenants always admit. A drained bucket sheds with the
        seconds until it refills past zero — the API turns that into a
        per-tenant 429 + Retry-After."""
        rate = self._tenant_rate(tenant)
        if rate <= 0:
            return True, 0.0
        now = time.monotonic() if now is None else now
        level = self._refill(tenant, rate, now)
        if level >= 0.0:
            return True, 0.0
        self.tenant_throttled[tenant] = self.tenant_throttled.get(tenant, 0) + 1
        return False, -level / rate

    def tenant_stats(self) -> dict[str, dict[str, float]]:
        """Per-tenant quota detail for /v1/debug/perf and the dashboard."""
        now = time.monotonic()
        out: dict[str, dict[str, float]] = {}
        for tenant in sorted(
            set(self.tenant_quotas) - {"*"}
            | set(self._tenant_level) | set(self.tenant_throttled)
        ):
            rate = self._tenant_rate(tenant)
            out[tenant] = {
                "quota_tok_per_s": rate,
                "bucket_tokens": (
                    self._refill(tenant, rate, now) if rate > 0 else 0.0
                ),
                "throttled_total": float(self.tenant_throttled.get(tenant, 0)),
                "charged_tokens": float(self.tenant_charged.get(tenant, 0)),
            }
        return out

    def drain_estimate_s(
        self,
        n_waiting: int,
        mean_tokens: float,
        decode_chunk: int,
        max_slots: int,
    ) -> float:
        """EMA-costed estimate of seconds until `n_waiting` queued requests
        could start: waves of `max_slots` requests, each running
        `mean_tokens / decode_chunk` decode rounds at the observed round
        EMA. Feeds the API's shed path (`Retry-After` on 429) — a coarse
        but finite, self-tuning number beats a constant."""
        waves = math.ceil(max(1, int(n_waiting)) / max(1, int(max_slots)))
        rounds = max(1.0, float(mean_tokens) / max(1, int(decode_chunk)))
        round_s = self.decode_round_s if self.decode_round_s > 0 else 0.05
        return waves * rounds * round_s

    def stats(self) -> dict[str, float]:
        return {
            "prefill_token_budget": float(self.last_budget),
            "starved_rounds": float(self.starved_rounds),
            "decode_round_ema_ms": self.decode_round_s * 1000.0,
            "prefill_tok_cost_us": self.prefill_tok_s * 1e6,
            "fair_cap_tokens": float(self.fair_cap()),
            "verify_rounds": float(self.verify_rounds),
            "verify_tokens": float(self.verify_tokens),
            "prefill_true_tokens": float(self.prefill_true_tokens),
            "prefill_padded_tokens": float(self.prefill_padded_tokens),
            "prefill_pad_waste_pct": (
                100.0
                * (1.0 - self.prefill_true_tokens / self.prefill_padded_tokens)
                if self.prefill_padded_tokens
                else 0.0
            ),
            # per-tenant quota contract keys (flat rollups; detail in
            # tenant_stats()) — pinned by tests/test_scheduler.py
            "tenant_quota_tenants": float(len(self.tenant_quotas)),
            "tenant_throttled_total": float(
                sum(self.tenant_throttled.values())
            ),
            "tenant_charged_tokens": float(
                sum(self.tenant_charged.values())
            ),
        }
