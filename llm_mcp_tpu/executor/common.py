"""Shared executor helpers."""

from __future__ import annotations


def pow2_bucket(n: int, cap: int, floor: int = 32) -> int:
    """Smallest power-of-two ≥ n (min `floor`), capped at `cap`.

    Prompt/batch padding buckets: each bucket shape compiles once under jit,
    so a handful of power-of-two sizes covers all input lengths.
    """
    b = floor
    while b < n:
        b *= 2
    return min(b, cap)


def fine_bucket(n: int, cap: int, floor: int = 32) -> int:
    """Smallest rung of the {pow2, 1.5x pow2} ladder ≥ n (min `floor`),
    capped at `cap` — 32, 48, 64, 96, 128, 192, 256, ...

    Prompt padding to pow2 buckets wastes ~25% of the prefill weight pass
    on average (uniform lengths fill a pow2 bucket ~75%); the midpoint
    rungs cut the mean waste to ~12% for one extra executable per octave.
    Mosaic tiling keeps the midpoints MXU-friendly (every rung ≥ 48 is a
    multiple of 16; sequence dims pad to lane tiles anyway).
    """
    b = floor
    while b < n:
        mid = b + b // 2
        if n <= mid:
            return min(mid, cap)
        b *= 2
    return min(b, cap)
