"""Shared executor helpers."""

from __future__ import annotations


def pow2_bucket(n: int, cap: int, floor: int = 32) -> int:
    """Smallest power-of-two ≥ n (min `floor`), capped at `cap`.

    Prompt/batch padding buckets: each bucket shape compiles once under jit,
    so a handful of power-of-two sizes covers all input lengths.
    """
    b = floor
    while b < n:
        b *= 2
    return min(b, cap)
