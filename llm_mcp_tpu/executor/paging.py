"""Paged KV subsystem: refcounted block tables with copy-on-write prefix
sharing (vLLM PagedAttention, Kwon et al. 2023; SGLang RadixAttention,
Zheng et al. 2024).

The engine's KV arena is carved into fixed-size blocks of
``TPU_KV_BLOCK_TOKENS`` token positions (default 64). Every live slot owns
an ordered *block table*; a prefix-cache hit **pins** the entry's full
blocks into the new slot's table (refcount++, no new allocation) instead
of being charged for a fresh copy, and the first partially-shared boundary
block is **copied-on-write** into a private block. The admission watermark
then compares *unique* blocks — shared tokens are paid for once no matter
how many slots reference them — and preemption snapshots only the private
tail (the shared pins ride along as ids and are re-pinned on restore).

Scope — this layer is the block *economy* and stays pure host
bookkeeping. Since the block-indirect PR the economy is also physical:
``executor/physical.py`` rebuilds per-slot device block tables from
``table_view()`` after every re-keying mutation, private blocks are
identity-homed in the slot arena, and prefix pins resolve to rows of a
separate device pool — so a prefix-cache hit admits with *zero* row
copies and attention kernels gather K/V through the table (see
doc/performance.md "Paged KV" for the honest accounting of what is and
isn't copied). Pool-row reclamation keys on ``alive()``: a pool row
outlives its evicted prefix entry for as long as sharer pins keep the
ledger id referenced.

One ledger (satellite of ISSUE 6): slot-arena blocks and prefix-cache
blocks are allocated from a single id space sized
``max_slots * blocks_per_slot + prefix_budget_bytes // block_bytes`` — the
two budgets can no longer jointly oversubscribe HBM behind each other's
backs.

Mirroring: every mutator returns a compact list of ops carrying **block
ids, never KV bytes**. A SliceEngine leader streams them to followers as a
single ``("blk", ops)`` command; ``apply_ops`` replays them
deterministically into a mirror manager. The manager is pure host
bookkeeping — no jax imports — so followers and unit tests replay it
byte-for-byte.

Threading: one internal OrderedLock (rank 30 — see doc/concurrency.md);
every public method is safe from the engine loop, watchdog, and HTTP
threads. Allocation never blocks serving: if bookkeeping ever drifts past
the ledger total (a bug), the allocator hands out an overflow id and
counts it — ``audit()`` and the bench's end-of-run leak counter surface
it, the request still runs.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Iterable

from ..utils.locks import OrderedLock

log = logging.getLogger("llm_mcp_tpu.paging")

DEFAULT_BLOCK_TOKENS = 64

# op tuples (first element is the kind) — the whole mirror protocol:
#   ("alloc",   slot, ids)                      fresh private blocks appended
#   ("pin",     slot, ids)                      shared blocks refcounted into table
#   ("cow",     slot, src_id, dst_id)           boundary block copied-on-write
#   ("free",    slot, ids)                      table dropped, blocks decref'd
#   ("snap",    snap_id, slot, shared, private) preempt: private freed, pins parked
#   ("restore", snap_id, slot, ids)             snap pins re-tabled + fresh private
#   ("drop",    snap_id)                        snapshot discarded, pins decref'd
#   ("pxalloc", key, ids, tokens)               prefix entry registered
#   ("pxfree",  key)                            prefix entry evicted


def block_tokens_from_env() -> int:
    raw = os.environ.get("TPU_KV_BLOCK_TOKENS", "")
    try:
        v = int(raw) if raw else DEFAULT_BLOCK_TOKENS
    except ValueError:
        log.warning("bad TPU_KV_BLOCK_TOKENS=%r; using %d", raw, DEFAULT_BLOCK_TOKENS)
        v = DEFAULT_BLOCK_TOKENS
    return max(1, v)


class PagedKVManager:
    """Refcounted block tables over the slot KV arena + prefix partition.

    All sizes are in *blocks* internally; callers speak tokens. Mutators
    return op lists for follower mirroring (empty when nothing changed);
    single-process engines simply discard them.
    """

    def __init__(
        self,
        *,
        max_slots: int,
        max_seq_len: int,
        block_tokens: int | None = None,
        bytes_per_token: int = 0,
        prefix_budget_bytes: int = 0,
    ):
        bt = block_tokens if block_tokens else block_tokens_from_env()
        self.block_tokens = max(1, int(bt))
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len)
        self.blocks_per_slot = -(-self.max_seq_len // self.block_tokens)
        self.bytes_per_token = int(bytes_per_token)
        self.bytes_per_block = self.bytes_per_token * self.block_tokens
        self.slot_partition = self.max_slots * self.blocks_per_slot
        self.prefix_partition = (
            int(prefix_budget_bytes) // self.bytes_per_block
            if self.bytes_per_block > 0
            else 0
        )
        self.total_blocks = self.slot_partition + self.prefix_partition

        self._lock = OrderedLock("paging", rank=30)
        # allocator: lazy fresh ids (`_next`) + recycled LIFO free list; the
        # list may hold stale entries (alloc_exact takes from the middle via
        # the set), skipped at pop time
        self._free: list[int] = []
        self._free_set: set[int] = set()
        self._next = 0
        self._rc: dict[int, int] = {}
        # ownership maps — every refcount is owed to exactly one row here;
        # audit() recomputes rc from these and flags any drift
        self._tables: dict[int, list[int]] = {}  # slot -> ordered block ids
        self._shared_n: dict[int, int] = {}  # slot -> leading pinned-shared count
        self._prefix: dict[Any, tuple[list[int], int]] = {}  # key -> (ids, tokens)
        self._snap_pins: dict[int, list[int]] = {}  # snap_id -> parked shared pins
        self._snap_need: dict[int, int] = {}  # snap_id -> private blocks to restore
        self._prefix_owned = 0

        # counters / economy stats
        self.allocs_total = 0
        self.frees_total = 0
        self.cow_copies_total = 0
        self.double_free_errors = 0
        self.ledger_overflow = 0
        self.admit_total = 0
        self.admit_shared_total = 0
        self.pinned_blocks_total = 0  # blocks NOT allocated thanks to sharing
        self.peak_sharing_ratio = 1.0
        # expected private-block cost of one queued admission, used to price
        # the admit queue in offered_blocks(); initialized to a full slot so
        # zero-sharing behavior reduces exactly to the old slot-count
        # accounting
        self._ema_admit_blocks = float(self.blocks_per_slot)

        # Observability tap (telemetry/recorder.py flight events): the
        # engine injects a callback that receives each sharing-relevant ops
        # list (pin / cow / snap / restore / drop / free-of-shared). The
        # callback runs UNDER the rank-30 paging lock, so it must be
        # non-blocking and must never take a ranked lock — the flight
        # recorder's append satisfies both. None (the default) is free.
        self.on_ops: "Callable[[list[tuple]], None] | None" = None

    # -- allocator core (callers hold self._lock) ---------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks covering n_tokens positions (>= 1)."""
        return max(1, -(-max(1, int(n_tokens)) // self.block_tokens))

    def _alloc_ids(self, n: int) -> list[int]:
        ids: list[int] = []
        for _ in range(n):
            bid = None
            while self._free:
                cand = self._free.pop()
                if cand in self._free_set:
                    self._free_set.discard(cand)
                    bid = cand
                    break
            if bid is None:
                bid = self._next
                self._next += 1
                if self._next > self.total_blocks:
                    self.ledger_overflow += 1
            self._rc[bid] = 1
            ids.append(bid)
        self.allocs_total += n
        return ids

    def _alloc_exact(self, ids: Iterable[int]) -> None:
        """Follower-side mirror of the leader's allocation choices."""
        for bid in ids:
            if bid in self._free_set:
                self._free_set.discard(bid)  # stale list entry skipped later
            elif bid >= self._next:
                self._next = bid + 1
            elif bid in self._rc:
                # leader and mirror streams diverged — count it, keep going
                self.ledger_overflow += 1
            self._rc[bid] = 1
            self.allocs_total += 1

    def _incref(self, bid: int) -> None:
        self._rc[bid] = self._rc.get(bid, 0) + 1

    def _decref(self, bid: int) -> None:
        rc = self._rc.get(bid)
        if rc is None:
            self.double_free_errors += 1
            return
        if rc <= 1:
            del self._rc[bid]
            self._free.append(bid)
            self._free_set.add(bid)
            self.frees_total += 1
        else:
            self._rc[bid] = rc - 1

    def _note_peak(self) -> None:
        used = len(self._rc)
        if used:
            logical = sum(self._rc.values())
            ratio = logical / used
            if ratio > self.peak_sharing_ratio:
                self.peak_sharing_ratio = ratio

    def _notify(self, ops: list[tuple]) -> list[tuple]:
        """Hand a mutation's ops to the injected observer (see on_ops in
        __init__) and return them unchanged, so callers tack it onto their
        return statement. Observer exceptions never break the ledger."""
        cb = self.on_ops
        if cb is not None and ops:
            try:
                cb(ops)
            except Exception:  # noqa: BLE001
                pass
        return ops

    # -- slot lifecycle -----------------------------------------------------

    def admit_slot(self, slot: int, n_tokens: int) -> list[tuple]:
        """Fresh (unshared) admission: allocate a private table covering
        n_tokens."""
        with self._lock:
            ops = self._free_slot_locked(slot)  # defensive: stale table
            ids = self._alloc_ids(self.blocks_for(n_tokens))
            self._tables[slot] = ids
            self._shared_n[slot] = 0
            self.admit_total += 1
            ops.append(("alloc", slot, list(ids)))
            return ops

    def admit_shared(self, slot: int, key: Any, n_tokens: int) -> list[tuple]:
        """Prefix-hit admission: pin the entry's full blocks (no
        allocation), copy-on-write the partial boundary block if the stored
        prefix doesn't end on a block edge, then extend privately to
        n_tokens. Falls back to admit_slot when the key is unknown (entry
        raced an eviction)."""
        with self._lock:
            ent = self._prefix.get(key)
            if ent is None:
                pass  # fall through to plain admission below
            else:
                entry_ids, p0 = ent
                ops = self._free_slot_locked(slot)
                full = p0 // self.block_tokens
                pinned = entry_ids[:full]
                for bid in pinned:
                    self._incref(bid)
                table = list(pinned)
                if pinned:
                    ops.append(("pin", slot, list(pinned)))
                    self.pinned_blocks_total += len(pinned)
                if p0 % self.block_tokens:
                    src = entry_ids[full]
                    dst = self._alloc_ids(1)[0]
                    table.append(dst)
                    self.cow_copies_total += 1
                    ops.append(("cow", slot, src, dst))
                need = self.blocks_for(n_tokens)
                if need > len(table):
                    extra = self._alloc_ids(need - len(table))
                    table.extend(extra)
                    ops.append(("alloc", slot, extra))
                self._tables[slot] = table
                self._shared_n[slot] = len(pinned)
                self.admit_total += 1
                self.admit_shared_total += 1
                self._note_peak()
                return self._notify(ops)
        return self.admit_slot(slot, n_tokens)

    def ensure_slot(self, slot: int, n_tokens: int) -> list[tuple]:
        """Extend an existing table to cover n_tokens, or admit a fresh one
        — the activation path's single entry point (the table may or may
        not predate it, depending on the chunked-prefill route)."""
        with self._lock:
            if slot in self._tables:
                return self._extend_locked(slot, n_tokens)
        return self.admit_slot(slot, n_tokens)

    def _extend_locked(self, slot: int, n_tokens: int) -> list[tuple]:
        table = self._tables.get(slot)
        if table is None:
            return []
        need = self.blocks_for(n_tokens)
        if need <= len(table):
            return []
        extra = self._alloc_ids(need - len(table))
        table.extend(extra)
        return [("alloc", slot, extra)]

    def extend(self, slot: int, n_tokens: int) -> list[tuple]:
        with self._lock:
            return self._extend_locked(slot, n_tokens)

    def extend_many(self, wants: dict[int, int]) -> list[tuple]:
        """Batched decode-path extend: one lock acquisition per round."""
        ops: list[tuple] = []
        with self._lock:
            for slot, n_tokens in wants.items():
                ops.extend(self._extend_locked(slot, n_tokens))
        return ops

    def free_slot(self, slot: int) -> list[tuple]:
        """Release a slot's table. Idempotent: a slot without a table (never
        admitted, or already preempted) is a no-op — _free_now is the
        engine's single release chokepoint and may fire after preempt."""
        with self._lock:
            return self._notify(self._free_slot_locked(slot))

    def _free_slot_locked(self, slot: int) -> list[tuple]:
        table = self._tables.pop(slot, None)
        self._shared_n.pop(slot, None)
        if not table:
            return []
        for bid in table:
            self._decref(bid)
        return [("free", slot, list(table))]

    def has_table(self, slot: int) -> bool:
        with self._lock:
            return slot in self._tables

    def covered_tokens(self, slot: int) -> int:
        with self._lock:
            table = self._tables.get(slot)
            return len(table) * self.block_tokens if table else 0

    def table_view(self, slot: int) -> tuple[list[int], int]:
        """Ordered block ids plus leading shared-pin count for one slot
        (copies). The physical layer (executor/physical.py) rebuilds its
        device block-table row from this after any mutation that re-keys
        the slot; logical position j in the returned list always covers
        token range [j*block_tokens, (j+1)*block_tokens)."""
        with self._lock:
            table = self._tables.get(slot)
            return (list(table) if table else [], self._shared_n.get(slot, 0))

    def alive(self, bid: int) -> bool:
        """True while a block id holds any reference (slot tables, prefix
        entries, parked snapshot pins). Pool-row reclamation keys on this:
        an evicted prefix entry's pool rows stay mapped until the last
        sharer pin lets the ledger id die."""
        with self._lock:
            return bid in self._rc

    def prefix_ids(self, key: Any) -> list[int] | None:
        """Ledger block ids of a registered prefix entry, or None when the
        key is unknown (raced an eviction)."""
        with self._lock:
            ent = self._prefix.get(key)
            return list(ent[0]) if ent else None

    def prefix_chains(self) -> list[tuple[Any, int]]:
        """Snapshot of resident prefix entries as ``(key, tokens)`` pairs
        — the routing tier's digest source (routing/prefix.py). Safe from
        any thread; a digest built from a snapshot that races an eviction
        only misprices one routing score until the next tag refresh."""
        with self._lock:
            return [(key, tokens) for key, (_, tokens) in self._prefix.items()]

    # -- preempt / restore --------------------------------------------------

    def preempt_slot(self, slot: int, snap_id: int) -> list[tuple]:
        """Preemption keeps only the shared pins (ids, zero bytes) parked
        under snap_id; the private tail is freed — its rows live in the
        host snapshot."""
        with self._lock:
            table = self._tables.pop(slot, None)
            sn = self._shared_n.pop(slot, 0)
            if table is None:
                return []
            shared, private = table[:sn], table[sn:]
            for bid in private:
                self._decref(bid)
            self._snap_pins[snap_id] = shared
            self._snap_need[snap_id] = len(private)
            return self._notify(
                [("snap", snap_id, slot, list(shared), list(private))]
            )

    def restore_slot(self, slot: int, snap_id: int, n_tokens: int) -> list[tuple]:
        """Re-table the parked shared pins and allocate a fresh private
        tail covering n_tokens."""
        with self._lock:
            pinned = self._snap_pins.pop(snap_id, [])
            self._snap_need.pop(snap_id, None)
            ops = self._free_slot_locked(slot)
            table = list(pinned)
            need = self.blocks_for(n_tokens)
            extra = self._alloc_ids(max(0, need - len(table)))
            table.extend(extra)
            self._tables[slot] = table
            self._shared_n[slot] = len(pinned)
            ops.append(("restore", snap_id, slot, list(extra)))
            return self._notify(ops)

    def drop_snap(self, snap_id: int) -> list[tuple]:
        """Discard a snapshot's parked pins (request aborted/finished while
        offloaded, or the pool drained). Idempotent."""
        with self._lock:
            pins = self._snap_pins.pop(snap_id, None)
            had_need = self._snap_need.pop(snap_id, None) is not None
            if pins is None and not had_need:
                return []
            for bid in pins or ():
                self._decref(bid)
            return self._notify([("drop", snap_id)])

    # -- prefix partition (the folded prefix budget) -------------------------

    def prefix_can_fit(self, n_tokens: int) -> bool:
        with self._lock:
            return self._prefix_owned + self.blocks_for(n_tokens) <= self.prefix_partition

    def prefix_register(self, key: Any, n_tokens: int) -> list[tuple] | None:
        """Claim blocks for a new prefix entry; None when the partition is
        full (caller evicts LRU entries and retries, or skips the store)."""
        with self._lock:
            if key in self._prefix:
                return []
            n = self.blocks_for(n_tokens)
            if self._prefix_owned + n > self.prefix_partition:
                return None
            ids = self._alloc_ids(n)
            self._prefix[key] = (ids, int(n_tokens))
            self._prefix_owned += n
            return [("pxalloc", key, list(ids), int(n_tokens))]

    def prefix_release(self, key: Any) -> list[tuple]:
        """Drop the cache's own reference; blocks stay alive while live
        tables or snapshots still pin them."""
        with self._lock:
            ent = self._prefix.pop(key, None)
            if ent is None:
                return []
            ids, _ = ent
            self._prefix_owned -= len(ids)
            for bid in ids:
                self._decref(bid)
            return [("pxfree", key)]

    # -- mirroring ----------------------------------------------------------

    def apply_ops(self, ops: Iterable[tuple]) -> None:
        """Replay a leader's op stream into this mirror. Ids are explicit —
        no allocation policy needs to match, only the stream order (one TCP
        channel preserves it)."""
        with self._lock:
            for op in ops:
                kind = op[0]
                if kind == "alloc":
                    _, slot, ids = op
                    self._alloc_exact(ids)
                    self._tables.setdefault(slot, [])
                    self._shared_n.setdefault(slot, 0)
                    self._tables[slot].extend(ids)
                elif kind == "pin":
                    _, slot, ids = op
                    for bid in ids:
                        self._incref(bid)
                    table = self._tables.setdefault(slot, [])
                    table.extend(ids)
                    self._shared_n[slot] = self._shared_n.get(slot, 0) + len(ids)
                    self.pinned_blocks_total += len(ids)
                elif kind == "cow":
                    _, slot, _src, dst = op
                    self._alloc_exact([dst])
                    self._tables.setdefault(slot, []).append(dst)
                    self._shared_n.setdefault(slot, 0)
                    self.cow_copies_total += 1
                elif kind == "free":
                    _, slot, _ids = op
                    self._free_slot_locked(slot)
                elif kind == "snap":
                    snap_id, slot = op[1], op[2]
                    table = self._tables.pop(slot, None)
                    sn = self._shared_n.pop(slot, 0)
                    if table is not None:
                        shared, private = table[:sn], table[sn:]
                        for bid in private:
                            self._decref(bid)
                        self._snap_pins[snap_id] = shared
                        self._snap_need[snap_id] = len(private)
                elif kind == "restore":
                    snap_id, slot, ids = op[1], op[2], op[3]
                    pinned = self._snap_pins.pop(snap_id, [])
                    self._snap_need.pop(snap_id, None)
                    self._free_slot_locked(slot)
                    self._alloc_exact(ids)
                    self._tables[slot] = list(pinned) + list(ids)
                    self._shared_n[slot] = len(pinned)
                elif kind == "drop":
                    snap_id = op[1]
                    pins = self._snap_pins.pop(snap_id, None)
                    self._snap_need.pop(snap_id, None)
                    for bid in pins or ():
                        self._decref(bid)
                elif kind == "pxalloc":
                    _, key, ids, tokens = op
                    if key not in self._prefix:
                        self._alloc_exact(ids)
                        self._prefix[key] = (list(ids), int(tokens))
                        self._prefix_owned += len(ids)
                elif kind == "pxfree":
                    key = op[1]
                    ent = self._prefix.pop(key, None)
                    if ent is not None:
                        ids, _ = ent
                        self._prefix_owned -= len(ids)
                        for bid in ids:
                            self._decref(bid)
                else:
                    raise ValueError(f"unknown paging op {kind!r}")
            self._note_peak()

    # -- admission economy --------------------------------------------------

    def note_admit_cost(self, n_blocks: int) -> None:
        """Record one admission's private-block commitment (allocated now +
        expected decode growth) for pricing the queue in offered_blocks()."""
        n = max(0.0, float(n_blocks))
        self._ema_admit_blocks = 0.8 * self._ema_admit_blocks + 0.2 * n

    def ema_admit_blocks(self) -> float:
        return self._ema_admit_blocks

    def offered_blocks(self, wants: dict[int, int], queued: int) -> float:
        """Offered load in unique-block terms for the admission watermark:

        - every block referenced by a live table or parked snapshot counts
          ONCE (this is where sharing multiplies capacity);
        - each live slot additionally reserves the blocks it is committed
          to grow into (``wants``: slot -> target token count — decode
          growth is a promise already made at admission);
        - parked snapshots reserve the private blocks their restore will
          re-allocate;
        - the admit queue is priced at the EMA private-block cost of recent
          admissions (initialized to a full slot, so with zero sharing this
          whole function reduces to the old slot-count accounting).

        Divide by blocks_per_slot for slot-equivalents.
        """
        with self._lock:
            seen: set[int] = set()
            for table in self._tables.values():
                seen.update(table)
            for pins in self._snap_pins.values():
                seen.update(pins)
            offered = float(len(seen))
            for slot, n_tokens in wants.items():
                table = self._tables.get(slot)
                have = len(table) if table else 0
                want = self.blocks_for(n_tokens)
                if want > have:
                    offered += want - have
            offered += float(sum(self._snap_need.values()))
            offered += max(0, int(queued)) * self._ema_admit_blocks
            return offered

    # -- observability ------------------------------------------------------

    def stats(self) -> dict[str, float]:
        with self._lock:
            used = len(self._rc)
            logical = sum(self._rc.values())
            return {
                "block_tokens": float(self.block_tokens),
                "blocks_per_slot": float(self.blocks_per_slot),
                "blocks_total": float(self.total_blocks),
                "blocks_used": float(used),
                "blocks_free": float(self.total_blocks - used),
                "logical_blocks": float(logical),
                "sharing_ratio": (logical / used) if used else 1.0,
                "peak_sharing_ratio": self.peak_sharing_ratio,
                "slot_tables": float(len(self._tables)),
                "prefix_entries": float(len(self._prefix)),
                "prefix_blocks": float(self._prefix_owned),
                "prefix_partition": float(self.prefix_partition),
                "snap_parked": float(len(self._snap_pins)),
                "pinned_blocks_total": float(self.pinned_blocks_total),
                "cow_copies_total": float(self.cow_copies_total),
                "allocs_total": float(self.allocs_total),
                "frees_total": float(self.frees_total),
                "double_free_errors": float(self.double_free_errors),
                "ledger_overflow": float(self.ledger_overflow),
                "admit_total": float(self.admit_total),
                "admit_shared_total": float(self.admit_shared_total),
                "ema_admit_blocks": self._ema_admit_blocks,
            }

    def audit(self) -> dict[str, int]:
        """Recompute refcounts from the ownership maps and diff against the
        allocator's ledger. All-zero means no leaks, no double frees, no
        drift — asserted at quiesce by the soak tests and hard-failed by
        perf_gate via the bench's paged_block_leaks counter."""
        with self._lock:
            want: dict[int, int] = {}
            for table in self._tables.values():
                for bid in table:
                    want[bid] = want.get(bid, 0) + 1
            for ids, _ in self._prefix.values():
                for bid in ids:
                    want[bid] = want.get(bid, 0) + 1
            for pins in self._snap_pins.values():
                for bid in pins:
                    want[bid] = want.get(bid, 0) + 1
            leaked = sum(1 for bid in self._rc if bid not in want)
            missing = sum(1 for bid in want if bid not in self._rc)
            mismatched = sum(
                1 for bid, n in want.items() if bid in self._rc and self._rc[bid] != n
            )
            return {
                "leaked_blocks": leaked,
                "missing_blocks": missing,
                "refcount_mismatches": mismatched,
                "double_free_errors": self.double_free_errors,
                "ledger_overflow": self.ledger_overflow,
            }

    def leak_count(self) -> int:
        """Single scalar for the bench line of record / perf gate."""
        a = self.audit()
        return (
            a["leaked_blocks"]
            + a["missing_blocks"]
            + a["refcount_mismatches"]
            + a["double_free_errors"]
        )
