"""Host-side n-gram drafter for self-speculative decoding.

Prompt-lookup drafting (PLD): each slot keeps an index of the n-grams seen
so far in its own token history (prompt + everything generated).  To draft,
the longest suffix of the history that matches an earlier n-gram is looked
up and the tokens that followed that earlier occurrence are proposed as the
draft continuation.  No second model, no device work — the draft is a pure
host-side dict probe, and the proposal is deterministic (the drafter puts
probability 1 on its proposal), which is what makes the engine's
rejection-sampling verify exact: accept draft `d` with probability
`p_target(d)`, resample rejections from the target with `d` zeroed out.

This module is deliberately dependency-free (no jax, no numpy): it runs on
the engine thread between device dispatches and is pinned import-clean by a
tier-1 lint test so it stays usable under `JAX_PLATFORMS=cpu` and inside
the follower processes of a slice engine.
"""

from __future__ import annotations


class NGramDrafter:
    """Per-slot n-gram index with longest-suffix-match drafting.

    Tokens are appended one at a time (prompt first, then each emitted
    token).  When the token at position ``i`` arrives, every n-gram that
    *ends* at position ``i - 1`` gains a known continuation (position
    ``i``), so that is the moment it is registered — the index never maps a
    suffix to itself.  Last occurrence wins: repeated n-grams point at
    their most recent continuation, which tracks loops and recent phrasing
    better than the first occurrence.
    """

    __slots__ = ("ids", "min_n", "max_n", "_index")

    def __init__(self, min_n: int = 2, max_n: int = 3) -> None:
        if min_n < 1:
            raise ValueError(f"min_n must be >= 1, got {min_n}")
        if max_n < min_n:
            raise ValueError(f"max_n ({max_n}) must be >= min_n ({min_n})")
        self.ids: list[int] = []
        self.min_n = min_n
        self.max_n = max_n
        # _index[n][ngram-tuple] -> position of the token that followed it
        self._index: dict[int, dict[tuple[int, ...], int]] = {
            n: {} for n in range(min_n, max_n + 1)
        }

    def append(self, tok: int) -> None:
        """Append one token; register the n-grams it completes."""
        ids = self.ids
        i = len(ids)
        for n in range(self.min_n, self.max_n + 1):
            if i - n >= 0:
                self._index[n][tuple(ids[i - n : i])] = i
        ids.append(tok)

    def extend(self, toks) -> None:
        for t in toks:
            self.append(int(t))

    def _match(self, seq: list[int]) -> int | None:
        """Continuation position in ``ids`` for the longest indexed suffix
        of ``seq`` (an (max_n)-gram match is more specific — and empirically
        more accurate — than a shorter one, so n is probed from ``max_n``
        down to ``min_n``), or None when no suffix has been seen before."""
        for n in range(min(self.max_n, len(seq)), self.min_n - 1, -1):
            pos = self._index[n].get(tuple(seq[-n:]))
            if pos is not None:
                return pos
        return None

    def draft(self, k: int) -> list[int]:
        """Propose up to ``k`` tokens continuing the current history.

        When a continuation runs off the end of the real history before
        filling ``k`` (the match landed near the tail — the common case for
        tight loops, since last occurrence wins), the VIRTUAL history
        (ids + draft-so-far) is re-probed: its suffix is an interior n-gram
        of the real history, so loops of any period extend to the full k
        instead of truncating at the history edge.  Returns an empty list
        when no suffix of the history has been seen before (or ``k <= 0``).
        """
        ids = self.ids
        n_ids = len(ids)
        if k <= 0 or n_ids < self.min_n:
            return []
        out: list[int] = []
        cursor: int | None = None  # position in ids of the next draft token
        while len(out) < k:
            if cursor is None or cursor >= n_ids:
                cursor = self._match(ids + out if out else ids)
                if cursor is None or cursor >= n_ids:
                    break
            out.append(ids[cursor])
            cursor += 1
        return out

    def __len__(self) -> int:
        return len(self.ids)
