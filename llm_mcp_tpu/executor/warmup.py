"""Warmup planner: cold start as a first-class, measured serving phase.

ROADMAP item 5's baseline is brutal: a fresh node pays ~248 s before its
first token (the serve path eats the whole executable zoo's XLA compiles
on demand), while a warm-cache boot pays ~21 s. Every ingredient for a
fix already exists and is measured — the CompileLedger knows exactly
which shapes cost what, PR 11 collapsed prefill to one executable per
pow2 T, and the persistent compile cache round-trips in tier-1. This
module is the missing orchestration: it takes the engine's *serving-shape
zoo* (the same (phase, key) vocabulary `_note_exec_shape` feeds the
ledger: admit/chunk/pf_rag/decode/fused/verify/restore), orders it by
measured compile cost x hit priority, AOT-compiles the **critical
prefix** synchronously at boot — first token needs exactly one admit
bucket + one prefill executable + one decode shape — and background-
compiles the rest on a low-priority thread while the engine serves.

Readiness is a three-state machine surfaced at `/v1/debug/warmup` and
honored by routing (a warming engine advertises reduced capacity via the
`warming` discovery tag instead of eating 4-minute TTFTs):

    cold -> first_token_ready -> fully_warm

Knobs: `TPU_WARMUP` (default 1; `0` is a TRUE no-op — no planner, no
synthetic compiles, byte-identical greedy output), `TPU_WARMUP_BG`
(default 1; `0` skips the background phase — only the critical prefix
warms). Background compiles only *stick* across boots when the
persistent compile cache is on (`TPU_COMPILE_CACHE`): an AOT
lower().compile() populates the XLA cache that the serve path's jit
call then hits, skipping the dominant cost.

Like migration.py this module is deliberately engine-agnostic and
jax-free: the engine hands in a `compile_fn(phase, key) -> wall_s|None`
closure plus its zoo, and tests drive the planner with fakes (injected
slow compiles) without touching an accelerator stack.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

log = logging.getLogger("executor.warmup")

__all__ = [
    "READINESS_STATES",
    "WarmupPlanner",
    "WarmupStep",
    "key_str",
    "pack_priors",
    "plan_steps",
    "priors_from_table",
    "select_critical",
    "warmup_bg_enabled",
    "warmup_enabled",
]

READINESS_STATES = ("cold", "first_token_ready", "fully_warm")

# Phases an AOT compile can be synthesized for from the shape key alone
# (mirrors telemetry/perf.py WARMUP_PHASES — duplicated as a literal so
# this module stays importable standalone; tests pin the two in sync).
PLANNABLE_PHASES = ("admit", "chunk", "decode", "pf_rag")


def warmup_enabled() -> bool:
    """``TPU_WARMUP=0`` is a TRUE no-op: no planner object, no AOT
    compiles, no readiness tag — greedy output must be token-identical
    either way (warmup only moves *when* executables compile)."""
    return os.environ.get("TPU_WARMUP", "1") not in ("0", "false", "no")


def warmup_bg_enabled() -> bool:
    """``TPU_WARMUP_BG=0`` skips the background phase: only the critical
    prefix warms synchronously, the rest of the zoo compiles on first
    dispatch exactly as before."""
    return os.environ.get("TPU_WARMUP_BG", "1") not in ("0", "false", "no")


def key_str(key: tuple) -> str:
    """The CompileLedger's key encoding (engine `_compile_obs`):
    colon-joined str() of the tuple parts — priors from a ledger table or
    an imported warmup pack match plan steps through this."""
    return ":".join(str(p) for p in key)


@dataclass
class WarmupStep:
    """One executable shape in the plan. `status` lifecycle:
    pending -> done (compiled, wall recorded) | skip (phase unplannable
    or planner stopped) | fail (compile_fn raised)."""

    phase: str
    key: tuple
    priority: float = 0.0
    critical: bool = False
    status: str = "pending"
    wall_s: float = 0.0

    def as_dict(self) -> dict[str, Any]:
        return {
            "phase": self.phase,
            "key": key_str(self.key),
            "priority": round(self.priority, 6),
            "critical": self.critical,
            "status": self.status,
            "wall_s": round(self.wall_s, 4),
        }


def priors_from_table(table: list[dict[str, Any]]) -> dict[tuple, dict]:
    """Index CompileLedger aggregates (ledger.table() rows, or a warmup
    pack's exported plan) by (phase, key string) for priority scoring.
    Malformed rows are dropped, not raised — a stale pack must never
    block a boot."""
    priors: dict[tuple, dict] = {}
    for row in table or []:
        try:
            phase = str(row["phase"])
            ks = str(row["key"])
            count = max(1, int(row.get("count", 1)))
            total = float(row.get("total_s", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        priors[(phase, ks)] = {"count": count, "cost_s": total / count}
    return priors


def pack_priors(
    table: list[dict[str, Any]], cap: int = 256
) -> list[dict[str, Any]]:
    """Normalize ledger rows for cross-residency reuse (the model zoo
    captures these at swap-out and feeds them to the next swap-in's
    start_warmup). Keeps only well-formed rows, ordered by total compile
    seconds descending — the shapes worth re-warming first — capped so a
    long residency's ledger can't bloat the parked entry."""
    rows: list[dict[str, Any]] = []
    for row in table or []:
        try:
            rows.append({
                "phase": str(row["phase"]),
                "key": str(row["key"]),
                "count": max(1, int(row.get("count", 1))),
                "total_s": float(row.get("total_s", 0.0)),
            })
        except (KeyError, TypeError, ValueError):
            continue
    rows.sort(key=lambda r: -r["total_s"])
    return rows[: max(1, int(cap))]


def _score(phase: str, key: tuple, priors: dict[tuple, dict]) -> float:
    """Measured compile cost x hit priority when the ledger has seen the
    shape; otherwise a small shape-derived heuristic (smaller shapes score
    higher — they are what the first requests actually dispatch)."""
    p = priors.get((phase, key_str(key)))
    if p is not None:
        return p["cost_s"] * p["count"]
    size = 1.0
    for part in key:
        if isinstance(part, bool):
            continue
        if isinstance(part, (int, float)) and part > 0:
            size *= float(part)
    # unmeasured: rank below every measured shape, smallest-first within
    return 1.0 / (1.0 + size) * 1e-6


def select_critical(
    zoo: list[tuple[str, tuple]], priors: dict[tuple, dict]
) -> list[tuple[str, tuple]]:
    """The first-token prefix: exactly one admit bucket + one prefill
    executable + one decode shape. With priors, each slot takes its
    most-valuable measured shape (the fleet's actual first-hit traffic);
    cold, each takes its smallest — a single short greedy probe dispatches
    admit(1, min bucket) then decode(min Ba), and that probe is what
    start_warmup runs."""
    picks: list[tuple[str, tuple]] = []
    for slot in ("admit", ("chunk", "pf_rag"), "decode"):
        phases = (slot,) if isinstance(slot, str) else slot
        cands = [(ph, k) for ph, k in zoo if ph in phases]
        if not cands:
            continue
        measured = [c for c in cands if (c[0], key_str(c[1])) in priors]
        if measured:
            picks.append(max(measured, key=lambda c: _score(*c, priors)))
        else:
            # smallest shape = what a 1-request probe compiles anyway
            picks.append(min(cands, key=lambda c: _key_size(c[1])))
    return picks


def _key_size(key: tuple) -> float:
    size = 1.0
    for part in key:
        if isinstance(part, bool):
            continue
        if isinstance(part, (int, float)) and part > 0:
            size *= float(part)
    return size


def plan_steps(
    zoo: list[tuple[str, tuple]],
    priors: dict[tuple, dict] | None = None,
    critical: list[tuple[str, tuple]] | None = None,
) -> list[WarmupStep]:
    """Order the zoo into a plan: critical prefix first (in slot order),
    then the rest by descending priority (measured cost x hits, ties to
    smaller shapes). Duplicate (phase, key) entries collapse — pow2
    ladders from config enumeration and ledger-observed keys overlap."""
    priors = priors or {}
    if critical is None:
        critical = select_critical(zoo, priors)
    crit_set = {(ph, key_str(k)) for ph, k in critical}
    seen: set[tuple[str, str]] = set()
    crit_steps: list[WarmupStep] = []
    rest: list[WarmupStep] = []
    for ph, k in list(critical) + list(zoo):
        ident = (ph, key_str(k))
        if ident in seen:
            continue
        seen.add(ident)
        step = WarmupStep(
            phase=ph, key=tuple(k), priority=_score(ph, tuple(k), priors),
            critical=ident in crit_set,
        )
        (crit_steps if step.critical else rest).append(step)
    rest.sort(key=lambda s: (-s.priority, _key_size(s.key)))
    return crit_steps + rest


class WarmupPlanner:
    """Drives a plan through an engine-supplied compile hook and exposes
    the readiness state machine. `compile_fn(phase, key)` returns the
    compile wall in seconds, or None when the phase cannot be AOT-compiled
    (the step records as `skip` — it will compile on first real dispatch,
    exactly the pre-warmup behavior). Exceptions record as `fail` and
    never propagate: warmup is an accelerant, not a gate."""

    def __init__(
        self,
        compile_fn: Callable[[str, tuple], float | None],
        steps: list[WarmupStep],
        *,
        throttle_s: float = 0.0,
        event: Callable[..., Any] | None = None,
    ):
        self._compile_fn = compile_fn
        self.steps = list(steps)
        self.throttle_s = max(0.0, float(throttle_s))
        self._event = event
        self._lock = threading.Lock()
        self._state = "cold"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.started_at = time.time()
        self.first_token_ready_at: float | None = None
        self.fully_warm_at: float | None = None

    # -- state machine ------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _advance(self, state: str) -> None:
        with self._lock:
            # monotone: never move left (fully_warm cannot regress)
            if READINESS_STATES.index(state) <= READINESS_STATES.index(self._state):
                return
            self._state = state
            now = time.time()
            if state == "first_token_ready":
                self.first_token_ready_at = now
            elif state == "fully_warm":
                self.fully_warm_at = now
                if self.first_token_ready_at is None:
                    self.first_token_ready_at = now
        if self._event is not None:
            try:
                self._event("warmup", state=state,
                            t_s=round(time.time() - self.started_at, 3))
            except Exception:  # noqa: BLE001 — telemetry must not gate boot
                pass
        log.info("warmup state -> %s", state)

    # -- execution ----------------------------------------------------------

    def _run_step(self, step: WarmupStep) -> None:
        t0 = time.perf_counter()
        try:
            wall = self._compile_fn(step.phase, step.key)
        except Exception as e:  # noqa: BLE001 — warmup never takes boot down
            step.status = "fail"
            step.wall_s = time.perf_counter() - t0
            log.warning("warmup compile %s %s failed: %s",
                        step.phase, step.key, e)
        else:
            if wall is None:
                step.status = "skip"
            else:
                step.status = "done"
                step.wall_s = float(wall)
        if self._event is not None:
            try:
                self._event(
                    "wu", phase=step.phase, key=key_str(step.key),
                    wall_ms=round(step.wall_s * 1e3, 1), outcome=step.status,
                    critical=step.critical,
                )
            except Exception:  # noqa: BLE001
                pass

    def run_critical(self) -> None:
        """Synchronous boot phase: compile the first-token prefix, then
        advertise first_token_ready. With an empty plan the engine is
        trivially warm."""
        for step in self.steps:
            if step.critical and step.status == "pending":
                self._run_step(step)
        self._advance("first_token_ready")
        if not any(s.status == "pending" for s in self.steps):
            self._advance("fully_warm")

    def start_background(self) -> None:
        """Compile the remaining zoo on a low-priority daemon thread while
        the engine serves; throttle_s sleeps between compiles keep the
        planner off the serve path's host CPU. Idempotent."""
        if not any(s.status == "pending" for s in self.steps):
            self._advance("fully_warm")
            return
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._bg_loop, name="warmup-bg", daemon=True
        )
        self._thread.start()

    def _bg_loop(self) -> None:
        for step in self.steps:
            if self._stop.is_set():
                break
            if step.status != "pending":
                continue
            self._run_step(step)
            if self.throttle_s:
                self._stop.wait(self.throttle_s)
        for step in self.steps:
            if step.status == "pending":
                step.status = "skip"  # stopped mid-plan: remainder on demand
        self._advance("fully_warm")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None

    # -- telemetry ----------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        with self._lock:
            state = self._state
        by_status: dict[str, int] = {}
        compiled_s = 0.0
        for s in self.steps:
            by_status[s.status] = by_status.get(s.status, 0) + 1
            if s.status == "done":
                compiled_s += s.wall_s
        return {
            "state": state,
            "steps": len(self.steps),
            "by_status": by_status,
            "critical": sum(1 for s in self.steps if s.critical),
            "bg_compiles_done": sum(
                1 for s in self.steps if s.status == "done" and not s.critical
            ),
            "compiled_s": round(compiled_s, 3),
            "started_at": self.started_at,
            "first_token_ready_s": (
                round(self.first_token_ready_at - self.started_at, 3)
                if self.first_token_ready_at else None
            ),
            "fully_warm_s": (
                round(self.fully_warm_at - self.started_at, 3)
                if self.fully_warm_at else None
            ),
            "plan": [s.as_dict() for s in self.steps],
        }
