"""HBM-resident embedding engine serving `/v1/embeddings`.

Replaces the reference's Ollama `/api/embed` proxy path
(`core/internal/api/handlers.go:1942-2015`): batch inputs run as one jitted
encoder forward per length bucket, entirely on TPU. Matryoshka `dimensions`
support is exact (truncate + renormalize) rather than the reference's
client-side truncation fallback (`handlers.go:2063-2078`).
"""

from __future__ import annotations

import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig, resolve_config
from ..models.embedder import init_embedder_params, embed_forward
from ..parallel.sharding import (
    embedder_param_specs,
    llama_param_specs,
    shard_pytree,
)
from .common import pow2_bucket
from .tokenizer import Tokenizer, load_tokenizer


class EmbeddingEngine:
    def __init__(
        self,
        model: str | ModelConfig = "tiny-embed",
        *,
        mesh=None,
        params: Any = None,
        tokenizer: Tokenizer | None = None,
        max_batch: int = 64,
        max_seq_len: int = 512,
        dtype: Any = jnp.bfloat16,
        seed: int = 0,
        weights_dir: str = "",
        quant: str = "",
    ):
        # a config.json beside the weights is authoritative, exactly as for
        # GenerationEngine. Two architectures serve embeddings:
        #   arch="encoder"  — bidirectional mean/cls pooling
        #                     (models/embedder.py; nomic-class)
        #   decoder configs — causal LM with last-token pooling
        #                     (models/llama.py:llama_encode; Qwen3-Embedding
        #                     checkpoints are Qwen3ForCausalLM, so their
        #                     config.json resolves here and real safetensors
        #                     load through the ordinary decoder mapping)
        self.cfg = resolve_config(model, weights_dir) if isinstance(model, str) else model
        self.decoder_arch = self.cfg.arch != "encoder"
        self.mesh = mesh
        self.max_batch = max_batch
        if self.cfg.arch == "encoder" and self.cfg.enc_pos == "learned":
            # a learned position table has exactly cfg.max_seq_len rows
            # (BERT: 512) — longer buckets would index past it
            max_seq_len = min(max_seq_len, self.cfg.max_seq_len)
        self.max_seq_len = max_seq_len
        self.tokenizer: Tokenizer = tokenizer or load_tokenizer(weights_dir)

        if self.decoder_arch:
            from ..models import init_llama_params
            from ..models.weights import load_llama_checkpoint
            from .engine import _has_safetensors

            if params is None and _has_safetensors(weights_dir):
                params = load_llama_checkpoint(
                    self.cfg, weights_dir, dtype=dtype, mesh=mesh
                )
            elif params is None:
                if quant == "int8":
                    from ..models.quant import init_llama_params_quantized

                    params = init_llama_params_quantized(
                        self.cfg, jax.random.PRNGKey(seed), scale_dtype=dtype
                    )
                else:
                    params = init_llama_params(
                        self.cfg, jax.random.PRNGKey(seed), dtype=dtype
                    )
            if quant == "int8":
                from ..models.quant import quantize_params

                params = quantize_params(params)  # no-op on int8 trees
        elif params is None:
            from .engine import _has_safetensors

            if _has_safetensors(weights_dir):
                # real encoder checkpoint (BERT/nomic naming) — quantize
                # after load when asked (encoder checkpoints are small
                # enough to materialize first, unlike the 8B decoder path)
                from ..models.weights import load_embedder_checkpoint

                params = load_embedder_checkpoint(
                    self.cfg, weights_dir, dtype=dtype, mesh=None
                )
                if quant == "int8":
                    from ..models.quant import quantize_params

                    params = quantize_params(params)
            elif quant == "int8":
                # direct int8 init: an 8B-class embedder's bf16 tree
                # (~15 GB) never fits beside activations on a 16 GB chip
                from ..models.embedder import init_embedder_params_quantized

                params = init_embedder_params_quantized(
                    self.cfg, jax.random.PRNGKey(seed), scale_dtype=dtype
                )
            else:
                params = init_embedder_params(
                    self.cfg, jax.random.PRNGKey(seed), dtype=dtype
                )
        elif quant == "int8":
            from ..models.quant import quantize_params

            params = quantize_params(params)
        if mesh is not None:
            specs = (
                llama_param_specs(self.cfg)
                if self.decoder_arch
                else embedder_param_specs(self.cfg)
            )
            if quant == "int8":
                # {"q","s"} leaves need the quantized spec shape (the same
                # step GenerationEngine takes before sharding int8 trees)
                from ..models.quant import quantized_specs

                specs = quantized_specs(specs)
            params = shard_pytree(params, specs, mesh)
        self.params = params

        cfg = self.cfg

        if self.decoder_arch:
            from ..models.llama import llama_encode

            @jax.jit
            def fwd(params, tokens, lengths):
                return llama_encode(cfg, params, tokens, lengths)

        else:

            @jax.jit
            def fwd(params, tokens, lengths):
                return embed_forward(cfg, params, tokens, lengths)

        self._fwd = fwd
        self._lock = threading.Lock()
        self.total_inputs = 0
        self.total_tokens = 0

    def _bucket(self, n: int) -> int:
        return pow2_bucket(n, self.max_seq_len)

    def prepare_ids(self, text: str) -> list[int]:
        """Tokenize one input exactly as `embed` feeds the forward pass
        (truncation + the trailing [SEP] for encoder tokenizers). The single
        source of truth for anything that must time or replay the REAL
        executable (bench.py's b1 latency breakdown)."""
        ids = self.tokenizer.encode(text)[: self.max_seq_len]
        eos = getattr(self.tokenizer, "eos_id", -1)
        if not self.decoder_arch and eos is not None and eos >= 0:
            # BERT-family encoders were trained on [CLS] … [SEP] frames; the
            # tokenizer wrapper adds [CLS] (bos) but not the trailing [SEP]
            if not ids or ids[-1] != eos:
                ids = ids[: self.max_seq_len - 1] + [eos]
        return ids

    def embed(
        self, texts: list[str], dimensions: int | None = None
    ) -> tuple[list[list[float]], int]:
        """Encode texts → (vectors, total_tokens). Batches of up to
        `max_batch`, padded per-batch to the longest bucket."""
        if not texts:
            return [], 0
        all_ids = [self.prepare_ids(t) for t in texts]
        total_tokens = sum(len(i) for i in all_ids)
        vectors: list[list[float]] = []

        with self._lock:
            for i in range(0, len(all_ids), self.max_batch):
                chunk = all_ids[i : i + self.max_batch]
                B = len(chunk)
                # batch axis pads to a pow2 bucket too: without it every
                # distinct final-chunk size compiles a fresh executable
                # (VERDICT r2 weak #7 — B=7 vs B=8 were separate compiles);
                # pad rows hold 1 dummy token and their vectors are dropped
                Bb = pow2_bucket(B, self.max_batch, floor=1)
                bucket = self._bucket(max(len(c) for c in chunk))
                tokens = np.zeros((Bb, bucket), dtype=np.int32)
                lengths = np.ones(Bb, dtype=np.int32)
                for j, ids in enumerate(chunk):
                    tokens[j, : len(ids)] = ids
                    lengths[j] = len(ids)
                out = np.asarray(
                    self._fwd(self.params, tokens, lengths), dtype=np.float32
                )[:B]
                if dimensions and 0 < dimensions < out.shape[1]:
                    out = out[:, :dimensions]
                    norms = np.maximum(np.linalg.norm(out, axis=1, keepdims=True), 1e-9)
                    out = out / norms
                vectors.extend(out.tolist())
            self.total_inputs += len(texts)
            self.total_tokens += total_tokens
        return vectors, total_tokens
