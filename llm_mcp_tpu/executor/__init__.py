from .tokenizer import ByteTokenizer, load_tokenizer
from .engine import GenerationEngine, GenRequest
from .embedding import EmbeddingEngine

__all__ = [
    "ByteTokenizer",
    "load_tokenizer",
    "GenerationEngine",
    "GenRequest",
    "EmbeddingEngine",
]
