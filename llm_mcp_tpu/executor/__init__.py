from .tokenizer import ByteTokenizer, load_tokenizer
from .engine import GenerationEngine, GenRequest
from .embedding import EmbeddingEngine
from .slice_engine import SliceEngine, SliceRequest

__all__ = [
    "ByteTokenizer",
    "load_tokenizer",
    "GenerationEngine",
    "GenRequest",
    "EmbeddingEngine",
    "SliceEngine",
    "SliceRequest",
]
