from .tokenizer import ByteTokenizer, load_tokenizer
from .engine import GenerationEngine, GenRequest, SliceEngine, SliceRequest
from .embedding import EmbeddingEngine
from .zoo import ModelZoo

__all__ = [
    "ByteTokenizer",
    "load_tokenizer",
    "GenerationEngine",
    "GenRequest",
    "EmbeddingEngine",
    "SliceEngine",
    "SliceRequest",
    "ModelZoo",
]
