"""Tokenizers for the TPU executor.

Two implementations behind one minimal interface (encode / decode /
streaming-decode / special ids):

  - `HFTokenizer`: wraps a `tokenizer.json` (HuggingFace `tokenizers` Rust
    lib) when a real checkpoint directory is configured — the production path
    for Llama-3.1 / nomic / qwen vocabularies.
  - `ByteTokenizer`: dependency-free UTF-8 byte fallback (259 ids) so every
    model — including randomly-initialized dev/bench models — can serve the
    full API without vocabulary files. Streaming decode buffers partial UTF-8
    sequences so multi-byte characters never split across SSE chunks.

The reference has no tokenizer at all (token counts arrive from Ollama's
response fields, `worker/llm_worker/main.py:471-479`); here token accounting
is exact and local.
"""

from __future__ import annotations

import os
from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...
    def decode_stream(self, pending: bytes, new_ids: list[int]) -> tuple[str, bytes]: ...
    def decode_flush(self, pending: bytes) -> str: ...


def utf8_hold(data: bytes) -> int:
    """How many trailing bytes form an INCOMPLETE UTF-8 sequence (0-3).

    Single source of truth for the streaming hold-back boundary scan; the
    native scanner (`native/bpe_tokenizer.cpp::utf8_hold`) mirrors this and
    is equivalence-tested against it.
    """
    for i in range(1, min(3, len(data)) + 1):
        b = data[-i]
        if b < 0x80:  # ASCII — sequence complete
            return 0
        if b >= 0xC0:  # lead byte of a 2-4 byte sequence
            need = 2 if b < 0xE0 else 3 if b < 0xF0 else 4
            return i if i < need else 0
        # else continuation byte — keep scanning backwards
    return 0


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: 0=pad, 1=bos, 2=eos, byte b → 3+b."""

    PAD, BOS, EOS = 0, 1, 2
    OFFSET = 3

    def __init__(self) -> None:
        self.vocab_size = 259
        self.pad_id = self.PAD
        self.bos_id = self.BOS
        self.eos_id = self.EOS

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [self.OFFSET + b for b in text.encode("utf-8")]
        return ([self.BOS] + ids) if add_bos else ids

    def _bytes(self, ids: list[int]) -> bytes:
        # Ids outside [OFFSET, OFFSET+256) are ignored: models may have a
        # vocab larger than 259 (padded for MXU-friendly shapes), so sampled
        # ids beyond the byte range decode to nothing rather than crashing.
        return bytes(i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256)

    def decode(self, ids: list[int]) -> str:
        return self._bytes(ids).decode("utf-8", errors="replace")

    def decode_stream(self, pending: bytes, new_ids: list[int]) -> tuple[str, bytes]:
        """Incremental decode: returns (complete_text, leftover_bytes).

        Leftover bytes are the tail of an incomplete UTF-8 multi-byte
        sequence, to be prepended on the next call.
        """
        data = pending + self._bytes(new_ids)
        # Hold back only a genuinely incomplete trailing multi-byte sequence
        # (≤3 continuation-pending bytes); everything before it decodes now,
        # with invalid bytes becoming U+FFFD — a model emitting garbage bytes
        # must not stall the stream by buffering forever.
        hold = utf8_hold(data)
        if hold:
            return data[:-hold].decode("utf-8", errors="replace"), data[-hold:]
        return data.decode("utf-8", errors="replace"), b""

    def decode_flush(self, pending: bytes) -> str:
        """Decode whatever is still buffered at end of stream."""
        return pending.decode("utf-8", errors="replace") if pending else ""


class HFTokenizer:
    """Wrapper over a HuggingFace `tokenizer.json` file."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        # -1 = unresolved (same convention as BPETokenizer): a real vocab
        # token at id 0 must not be masked/stripped just because the file has
        # no recognizable pad/bos/eos names
        self.pad_id = self._special("<|finetune_right_pad_id|>", "<pad>", "[PAD]")
        self.bos_id = self._special("<|begin_of_text|>", "<s>", "[CLS]", "<bos>")
        self.eos_id = self._special(
            "<|end_of_text|>", "<|eot_id|>", "</s>", "[SEP]", "<eos>", "<end_of_turn>"
        )

    def _special(self, *names: str) -> int:
        for n in names:
            i = self._tok.token_to_id(n)
            if i is not None:
                return i
        return -1

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos and self.bos_id >= 0 else ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def decode_stream(self, pending: bytes, new_ids: list[int]) -> tuple[str, bytes]:
        # HF decode is stateless per call; pending carries undecoded ids as
        # a packed bytes blob of little-endian int32s.
        import struct

        prev = list(struct.unpack(f"<{len(pending) // 4}i", pending)) if pending else []
        ids = prev + new_ids
        text = self.decode(ids)
        # Hold back ids while the text ends with a replacement char (a
        # byte-fallback token mid-sequence) — but only up to 8 ids: a UTF-8
        # char spans ≤4 byte tokens, so a longer replacement-ending run means
        # the model really emitted U+FFFD-producing ids; flush them rather
        # than stalling the stream forever.
        if text.endswith("�") and len(ids) < 8:
            return "", struct.pack(f"<{len(ids)}i", *ids)
        return text, b""

    def decode_flush(self, pending: bytes) -> str:
        import struct

        if not pending:
            return ""
        ids = list(struct.unpack(f"<{len(pending) // 4}i", pending))
        return self.decode(ids)


def load_tokenizer(weights_dir: str = "") -> Tokenizer:
    """Tokenizer for a weights dir: the in-repo native BPE when a
    `tokenizer.json` exists (C++ merge core via ctypes, Python-merge
    fallback), the HF `tokenizers` wrapper on request or when the file uses
    a non-BPE model, else the dependency-free byte tokenizer.

    `LLM_MCP_TPU_TOKENIZER=native|python|hf|byte` forces a backend.
    """
    if weights_dir:
        path = os.path.join(weights_dir, "tokenizer.json")
        if os.path.exists(path):
            choice = os.environ.get("LLM_MCP_TPU_TOKENIZER", "native")
            if choice == "byte":
                return ByteTokenizer()
            if choice in ("native", "python"):
                try:
                    from .bpe import BPETokenizer

                    return BPETokenizer(path, force_python=(choice == "python"))
                except Exception as e:  # non-BPE model / missing regex: try HF
                    import logging

                    logging.getLogger("executor").warning(
                        "native BPE unavailable for %s (%s); trying HF", path, e
                    )
            if choice == "hf":
                # explicitly forced backend: fail loudly, never degrade
                return HFTokenizer(path)
            try:
                return HFTokenizer(path)
            except ImportError as e:
                import logging

                logging.getLogger("executor").error(
                    "no tokenizer backend available for %s (%s); degrading to "
                    "BYTE tokenizer — decoded text will not match the model's "
                    "vocabulary. Install `regex` or `tokenizers`.", path, e,
                )
                return ByteTokenizer()
    return ByteTokenizer()
