"""Model zoo: multi-model HBM residency with LRU host-RAM paging.

The reference routes across MANY models with quality tiers and per-device
RAM→params limits; our engines each serve exactly one model. This module is
the layer between the serve path and the engines that closes that gap on a
single chip: a few *hot* models stay resident in HBM, the long tail parks
its weights in host RAM, and a request for a parked model triggers a
swap — evict the least-recently-used resident, page the requested weights
back in, and ride the warmup path so the swapped-in model's first token
reuses the AOT plan + persistent compile cache instead of paying cold
XLA walls.

Mechanics, all built from machinery that already exists:

  - **Residency accounting** rides KVPool's layout-agnostic byte census
    (`pytree_nbytes` over the live param tree — bf16, int8 `{q, s}` dicts
    and MLA latents all count without layout-specific code). The zoo
    partitions an HBM budget (`hbm_budget_bytes`; 0 = count-only) across
    residents: a swap-in that would overflow it evicts LRU residents
    first, exactly like the pool's watermark sheds work it cannot hold.
  - **Swap-out** is `jax.device_get` of the engine's param tree — the
    same host-offload move KVPool makes for preempted KV — followed by
    engine shutdown (which frees HBM weights, KV cache and slots).
  - **Swap-in** constructs a fresh engine around the parked host tree
    (`GenerationEngine(params=host_tree)` — quantize/fuse re-run but are
    idempotent no-ops on an already-processed tree) and calls
    `start_warmup(priors)` with the compile-ledger rows captured at the
    model's last residency, so the critical first-token prefix compiles
    from the persistent cache before the first request lands.
  - **Routing**: `residency_band()` gives the router a 0/1/2 sort key
    (resident / parked / unknown) so quality tiers resolve to a resident
    model first and a swappable one second (routing/router.py).

Flight-recorder etypes (telemetry/recorder.py census): `zoo` on
registration and residency changes, `swap_in` / `swap_out` with byte
counts and wall seconds — the post-mortem trail for "why did this
request's first token take 4 s".

Thread safety: swaps serialize on one lock (a swap is seconds of work;
two concurrent swaps of the same 16 GB tree would be memory suicide).
`get()` on a resident model is lock-cheap and touch-only. Everything here
is opt-in: no ModelZoo ⇒ single-engine serving byte-identical to the
pre-zoo era.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

from ..telemetry.recorder import get_recorder
from .memory import pytree_nbytes

__all__ = ["ModelZoo"]

log = logging.getLogger("zoo")


class _ZooEntry:
    __slots__ = (
        "name", "engine", "host_params", "priors", "weight_bytes",
        "last_used", "swaps_in", "swaps_out", "last_swap_in_s",
        "last_swap_out_s",
    )

    def __init__(self, name: str):
        self.name = name
        self.engine: Any = None       # resident GenerationEngine, or None
        self.host_params: Any = None  # parked host-RAM param tree, or None
        self.priors: list[dict] = []  # compile-ledger rows from last residency
        self.weight_bytes = 0
        self.last_used = 0.0
        self.swaps_in = 0
        self.swaps_out = 0
        self.last_swap_in_s = -1.0
        self.last_swap_out_s = -1.0


class ModelZoo:
    """Co-host several models on one chip; see module docstring.

    `engine_factory(model_name, host_params)` builds (and does NOT start)
    a `GenerationEngine` for `model_name`; `host_params=None` means a cold
    first load (checkpoint / init), a tree means a swap-in of parked
    weights. The factory owns every construction kwarg (mesh, dtype,
    quant, slots) so boot wires them exactly once (api/__main__.py).
    """

    def __init__(
        self,
        engine_factory: Callable[[str, Any], Any],
        *,
        hot: int = 1,
        swap: bool = True,
        hbm_budget_bytes: int = 0,
    ):
        self._factory = engine_factory
        self.hot = max(1, int(hot))
        self.swap = bool(swap)
        self.hbm_budget_bytes = max(0, int(hbm_budget_bytes))
        self._entries: dict[str, _ZooEntry] = {}
        self._lock = threading.RLock()
        self.swaps_in_total = 0
        self.swaps_out_total = 0

    # -- registration ------------------------------------------------------

    def register(self, name: str, *, resident: bool = False) -> None:
        """Add `name` to the zoo's catalog. `resident=True` loads and
        starts it immediately (boot-time hot set); otherwise the first
        request pays the swap-in."""
        with self._lock:
            if name in self._entries:
                return
            self._entries[name] = _ZooEntry(name)
            get_recorder().event(
                "zoo", model=name, action="register", resident=resident,
                catalog=len(self._entries),
            )
        if resident:
            self.swap_in(name)

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def resident_models(self) -> list[str]:
        with self._lock:
            return sorted(
                n for n, e in self._entries.items() if e.engine is not None
            )

    def residency(self, name: str) -> str:
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                return "unknown"
            return "resident" if e.engine is not None else "parked"

    def residency_band(self, name: str) -> int:
        """Router sort key: resident models first (0), swappable second
        (1), models the zoo does not manage last (2)."""
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                return 2
            if e.engine is not None:
                return 0
            return 1 if self.swap else 2

    # -- request path ------------------------------------------------------

    def get(self, name: str) -> Any:
        """The engine serving `name`, swapping it in if parked. Raises
        KeyError for models outside the catalog and RuntimeError when the
        model is parked and swapping is disabled (TPU_ZOO_SWAP=0: the
        router should never have sent the request here — band 2)."""
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                raise KeyError(f"model {name!r} not in the zoo")
            if e.engine is not None:
                e.last_used = time.monotonic()
                return e.engine
            if not self.swap:
                raise RuntimeError(
                    f"model {name!r} is parked and TPU_ZOO_SWAP is off"
                )
        return self.swap_in(name)

    # -- swap machinery ----------------------------------------------------

    def _hbm_resident_bytes_locked(self) -> int:
        return sum(
            e.weight_bytes for e in self._entries.values()
            if e.engine is not None
        )

    def _evict_for_locked(self, incoming_bytes: int) -> list[str]:
        """LRU residents that must leave before `incoming_bytes` fit:
        count over `hot`, or bytes over the HBM budget (when set)."""
        victims: list[str] = []
        residents = sorted(
            (e for e in self._entries.values() if e.engine is not None),
            key=lambda e: e.last_used,
        )
        n_res = len(residents)
        used = self._hbm_resident_bytes_locked()
        for e in residents:
            # +1 for the incoming model, which is not yet in `residents`
            over_count = n_res - len(victims) + 1 > self.hot
            over_bytes = (
                self.hbm_budget_bytes > 0
                and used + incoming_bytes > self.hbm_budget_bytes
            )
            if not (over_count or over_bytes):
                break
            victims.append(e.name)
            used -= e.weight_bytes
        return victims

    def swap_in(self, name: str) -> Any:
        """Make `name` resident: evict LRU residents past the hot/budget
        limits, build an engine around the parked tree (or cold-load on
        first touch), start it, and warm it from the model's last
        residency's compile priors. Returns the started engine."""
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                raise KeyError(f"model {name!r} not in the zoo")
            if e.engine is not None:
                e.last_used = time.monotonic()
                return e.engine
            # size the incoming tree from its parked bytes; a cold first
            # load is unknown (0) and only the count limit applies to it
            incoming = pytree_nbytes(e.host_params) if e.host_params is not None else 0
            for victim in self._evict_for_locked(incoming):
                self._swap_out_locked(self._entries[victim])
            t0 = time.perf_counter()
            eng = self._factory(name, e.host_params)
            eng.start()
            eng.start_warmup(e.priors or None)
            dt = time.perf_counter() - t0
            e.engine = eng
            e.host_params = None  # the tree lives in HBM now; drop host copy
            e.weight_bytes = pytree_nbytes(eng.params)
            e.last_used = time.monotonic()
            e.swaps_in += 1
            e.last_swap_in_s = dt
            self.swaps_in_total += 1
            get_recorder().event(
                "swap_in", model=name, seconds=round(dt, 3),
                bytes=e.weight_bytes, warm_priors=len(e.priors),
                resident=len(self.resident_models()),
            )
            log.info(
                "zoo swap-in %s: %.2fs, %.1f MB weights, %d residents",
                name, dt, e.weight_bytes / 1e6,
                sum(1 for x in self._entries.values() if x.engine is not None),
            )
            return eng

    def swap_out(self, name: str) -> None:
        """Park `name`'s weights in host RAM and free its engine."""
        with self._lock:
            e = self._entries.get(name)
            if e is None or e.engine is None:
                return
            self._swap_out_locked(e)

    def _swap_out_locked(self, e: _ZooEntry) -> None:
        import jax

        eng = e.engine
        t0 = time.perf_counter()
        # host offload first (mirrors KVPool's device_get of preempted KV):
        # the tree must be safe in host RAM before shutdown frees HBM
        e.host_params = jax.device_get(eng.params)
        # carry the compile priors to the next residency so swap-in's
        # warmup re-plans from measured cost × hit count, not from scratch
        try:
            e.priors = eng.warmup_priors()
        except Exception:
            e.priors = []
        eng.shutdown()
        dt = time.perf_counter() - t0
        e.engine = None
        e.weight_bytes = pytree_nbytes(e.host_params)
        e.swaps_out += 1
        e.last_swap_out_s = dt
        self.swaps_out_total += 1
        get_recorder().event(
            "swap_out", model=e.name, seconds=round(dt, 3),
            bytes=e.weight_bytes,
        )
        log.info(
            "zoo swap-out %s: %.2fs, %.1f MB parked", e.name, dt,
            e.weight_bytes / 1e6,
        )

    def shutdown(self) -> None:
        """Stop every resident engine (process teardown; nothing parks)."""
        with self._lock:
            for e in self._entries.values():
                if e.engine is not None:
                    e.engine.shutdown()
                    e.engine = None

    # -- telemetry ---------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        """The /v1/debug/zoo document: per-model residency + HBM
        partition (weights from the zoo census, KV from each resident
        engine's own pool accounting)."""
        with self._lock:
            models: dict[str, Any] = {}
            for name, e in self._entries.items():
                kv_bytes = 0.0
                if e.engine is not None:
                    try:
                        kv_bytes = float(
                            e.engine.memory_stats().get("hbm_bytes", 0.0)
                        )
                    except Exception:
                        kv_bytes = 0.0
                models[name] = {
                    "residency": (
                        "resident" if e.engine is not None else "parked"
                    ),
                    "weight_bytes": float(e.weight_bytes),
                    "kv_bytes": kv_bytes,
                    "swaps_in": float(e.swaps_in),
                    "swaps_out": float(e.swaps_out),
                    "last_swap_in_s": e.last_swap_in_s,
                    "last_swap_out_s": e.last_swap_out_s,
                    "warm_priors": float(len(e.priors)),
                }
            return {
                "hot": float(self.hot),
                "swap_enabled": self.swap,
                "hbm_budget_bytes": float(self.hbm_budget_bytes),
                "hbm_resident_bytes": float(self._hbm_resident_bytes_locked()),
                "resident": sum(
                    1 for e in self._entries.values() if e.engine is not None
                ),
                "parked": sum(
                    1 for e in self._entries.values() if e.engine is None
                ),
                "swaps_in_total": float(self.swaps_in_total),
                "swaps_out_total": float(self.swaps_out_total),
                "models": models,
            }
