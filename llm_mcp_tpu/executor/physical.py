"""Physical half of the paged KV subsystem (vLLM PagedAttention, Kwon et
al. 2023): per-slot device block tables plus a prefix block pool.

``paging.PagedKVManager`` is the block *economy* — refcounted ids, no
bytes. This module makes those ids physical with one deliberate twist,
the **identity home**: a slot's private block at logical index ``j``
always lives at physical id ``slot * blocks_per_slot + j``, i.e. exactly
where the contiguous layout already put it. Only *shared* (prefix-pinned)
blocks resolve elsewhere — to rows of a separate device pool sized by the
prefix partition. Consequences:

- every existing KV **write** path (append kernels, chunked-prefill
  scatter, restore inserts, admission) is untouched — decode/prefill
  writes target private positions, and private positions are identity;
- a table row that references no shared blocks *is* the identity
  permutation, so the attention wrappers can runtime-detect the
  no-sharing case and keep the exact contiguous dispatch (raw-decode
  perf is not taxed by indirection it doesn't use);
- the table padding value for positions beyond a slot's ledger table is
  the identity home itself — a sentinel that is always safe to
  dereference (the kernels never read past ``nblk(length)``, and parked
  slots keep ``lengths == max_seq_len`` so they stream exactly one
  block).

Physical ids are ``[0, n_slots * blocks_per_slot)`` for arena homes and
``[pool_base, pool_base + pool_rows)`` for pool rows, with
``pool_base = n_slots * blocks_per_slot``; kernels and gather helpers
split on ``phys < pool_base``.

Pool rows are owned by ledger ids, not prefix keys: ``register_prefix``
maps a prefix entry's ledger ids to pool rows, and ``sweep`` reclaims a
row only once ``PagedKVManager.alive()`` says the ledger id died — an
evicted entry's rows stay readable while sharer pins keep the id alive.

Host bookkeeping is numpy-only; the device table is uploaded lazily on
``device_table()`` after mutations. A small lock guards the table since
free/preempt paths can race the engine loop.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Iterable

import numpy as np

log = logging.getLogger("llm_mcp_tpu.physical")


def pool_like(cache: Any, pool_rows: int, block_tokens: int) -> Any:
    """Allocate a prefix pool pytree mirroring a KV cache pytree.

    Every cache leaf is ``[L, B, heads, S, *rest]`` (rest may be empty —
    int8 scale planes are ``[L, B, heads, S]``); the pool leaf swaps the
    slot axis for ``pool_rows`` and the S axis for ``block_tokens``:
    ``[L, pool_rows, heads, block_tokens, *rest]``. One pool row holds
    one block's tokens across *all* layers, matching the ledger's
    bytes-per-block accounting.
    """
    import jax
    import jax.numpy as jnp

    def leaf(c):
        shape = (c.shape[0], pool_rows, c.shape[2], block_tokens) + c.shape[4:]
        return jnp.zeros(shape, dtype=c.dtype)

    return jax.tree.map(leaf, cache)


class PhysicalPool:
    """Device block tables + pool-row allocator over the ledger's ids."""

    def __init__(
        self,
        *,
        n_slots: int,
        seq_len: int,
        block_tokens: int,
        pool_rows: int,
    ):
        if seq_len % block_tokens:
            raise ValueError("seq_len must be a multiple of block_tokens")
        self.n_slots = int(n_slots)
        self.block_tokens = int(block_tokens)
        self.nbs = seq_len // self.block_tokens  # blocks per slot
        self.pool_rows = int(pool_rows)
        self.pool_base = self.n_slots * self.nbs

        self._identity = np.arange(self.pool_base, dtype=np.int32).reshape(
            self.n_slots, self.nbs
        )
        self.table = self._identity.copy()
        self._lock = threading.Lock()
        self._dirty = True
        self._dev: Any = None

        self._phys: dict[int, int] = {}  # ledger block id -> pool row
        self._free: list[int] = list(range(self.pool_rows - 1, -1, -1))

        self.rebuilds_total = 0
        self.cow_copies_total = 0
        self.missing_pins = 0  # shared pin with no pool mapping (bug tripwire)
        self.pool_rows_peak = 0

    # -- pool-row ownership --------------------------------------------------

    def register_prefix(self, ledger_ids: Iterable[int]) -> list[int] | None:
        """Map a prefix entry's ledger ids to fresh pool rows; None when
        the pool is out of rows (caller releases the ledger entry and
        skips the store — the partition and the pool are sized from the
        same budget, so this only fires when sweep is lagging pins)."""
        ids = list(ledger_ids)
        with self._lock:
            if len(self._free) < len(ids):
                return None
            rows = [self._free.pop() for _ in ids]
            for bid, row in zip(ids, rows):
                self._phys[bid] = row
            used = self.pool_rows - len(self._free)
            if used > self.pool_rows_peak:
                self.pool_rows_peak = used
            return rows

    def phys_of(self, ledger_id: int) -> int | None:
        """Physical id (pool_base + row) for a prefix-mapped ledger id."""
        with self._lock:
            row = self._phys.get(ledger_id)
            return None if row is None else self.pool_base + row

    def sweep(self, alive: Callable[[int], bool]) -> int:
        """Reclaim pool rows whose ledger id died. Called after prefix
        evictions and slot frees; cost is one dict scan."""
        with self._lock:
            dead = [bid for bid in self._phys if not alive(bid)]
            for bid in dead:
                self._free.append(self._phys.pop(bid))
            return len(dead)

    # -- table maintenance ---------------------------------------------------

    def rebuild(self, slot: int, ids: list[int], shared_n: int) -> bool:
        """Re-key one slot's table row from its ledger ``table_view``.
        Shared pins resolve through the pool map; everything else —
        private blocks, COW destinations, and padding past the ledger
        table — is the identity home. Returns True when the row changed."""
        row = self._identity[slot].copy()
        with self._lock:
            for j in range(min(shared_n, len(ids), self.nbs)):
                prow = self._phys.get(ids[j])
                if prow is None:
                    self.missing_pins += 1  # identity home = stale bytes; audited
                else:
                    row[j] = self.pool_base + prow
            if np.array_equal(row, self.table[slot]):
                return False
            self.table[slot] = row
            self._dirty = True
            self.rebuilds_total += 1
            return True

    def reset(self, slot: int) -> bool:
        """Back to identity (slot freed / preempted). Returns True when
        the row changed."""
        with self._lock:
            if np.array_equal(self.table[slot], self._identity[slot]):
                return False
            self.table[slot] = self._identity[slot]
            self._dirty = True
            return True

    def reset_all(self) -> None:
        with self._lock:
            self.table[:] = self._identity
            self._dirty = True

    def device_table(self) -> Any:
        """Device copy of the table, re-uploaded only after mutations."""
        import jax.numpy as jnp

        with self._lock:
            if self._dirty or self._dev is None:
                self._dev = jnp.asarray(self.table)
                self._dirty = False
            return self._dev

    # -- read-side helpers ---------------------------------------------------

    def row_sources(self, slot: int, nblocks: int) -> list[tuple[bool, int, int]]:
        """Host-side decode of one slot's first ``nblocks`` table entries
        for the rare gather paths (snapshot / prefix store / wire export):
        ``(in_arena, arena_row_or_pool_row, token_offset)`` per block."""
        out: list[tuple[bool, int, int]] = []
        with self._lock:
            row = self.table[slot, : max(0, min(nblocks, self.nbs))].tolist()
        for phys in row:
            if phys < self.pool_base:
                out.append((True, phys // self.nbs, (phys % self.nbs) * self.block_tokens))
            else:
                out.append((False, phys - self.pool_base, 0))
        return out

    def stats(self) -> dict[str, float]:
        with self._lock:
            return {
                "physical_pool_rows": float(self.pool_rows),
                "physical_pool_rows_used": float(self.pool_rows - len(self._free)),
                "physical_pool_rows_peak": float(self.pool_rows_peak),
                "physical_rebuilds_total": float(self.rebuilds_total),
                "physical_cow_copies_total": float(self.cow_copies_total),
                "physical_missing_pins": float(self.missing_pins),
            }
