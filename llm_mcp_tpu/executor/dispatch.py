"""Dispatch plane: ONE scheduling loop, two backends.

`GenerationEngine` (executor/engine.py) owns ALL policy — admission,
token budgets, speculation, preemption, paging, the prefix tier. Every
mutation of device state funnels through a single choke point
(`GenerationEngine._dx(op, *args)`), and a `DispatchBackend` decides what
a dispatch *means*:

  - **LocalArraysBackend** — today's single-process path. `emit` is a
    no-op; `_dx` just executes the op closure against local arrays.
    Zero overhead, byte-identical behavior to the pre-dispatch engine.
  - **GSPMDBackend** — the multi-host path. The leader broadcasts each
    dispatch as a `("step", op, args)` frame over the command channel
    BEFORE executing it locally; followers replay the identical op
    closure against the same born-sharded global arrays. Multi-controller
    JAX treats the identical numpy payloads as replicated inputs, so the
    jitted programs — and therefore the tokens — cannot diverge.

The step-program is the WHOLE protocol. A follower's loop is four lines:
ping → continue, stop → return, step → `exec_table[op](*args)`. There is
no per-feature command handling anywhere — not here, not in the engine —
and the llmtpu-lint dispatch-surface pass keeps it that way: every op the
engine registers/dispatches must appear in `DISPATCH_OPS` below, and the
channel classes may not be touched outside this module.

Payload discipline (what makes replay sound): op args carry only host
values — numpy arrays, ints, floats, strings, bytes. Device state (the
weights, the KV cache, the physical pool, sampling rows) lives on `self`
inside the op closures, identical on every process by born-sharded
construction. Anything the leader must READ back (sampled tokens,
snapshot rows, prefix exports) comes out of a jit with a REPLICATED
out-sharding, so `np.asarray` on it is a local copy on every process.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Mapping

__all__ = [
    "DISPATCH_OPS",
    "PING_INTERVAL_S",
    "CmdLeader",
    "CmdFollower",
    "DispatchBackend",
    "LocalArraysBackend",
    "GSPMDBackend",
]


# ---------------------------------------------------------------------------
# The op vocabulary: the COMPLETE device-mutation surface of the engine.
# llmtpu-lint (analysis/dispatch_surface.py) reconciles this tuple against
# the `ops[...] = ...` registry and every `_dx("...")` call site in
# engine.py, both ways — an op added on one side without the other fails CI.
# ---------------------------------------------------------------------------

DISPATCH_OPS = (
    "admit",    # fused admit: prefill + inserts + sampling rows + token0
    "insert",   # bulk row insert from a device prefix entry (hit, restore)
    "insrows",  # bulk row insert from host KV rows (restore, migrate-in)
    "insat",    # exact-length host-row insert at an offset (paged restore)
    "chunk",    # bucketed chunked-prefill group (logits park by gid)
    "ragged",   # ragged chunked-prefill group (logits park by gid)
    "bsample",  # boundary sample off a parked group's logits + row writes
    "decode",   # decode round (plain / fused-chunk / fused-ragged)
    "verify",   # speculative verify round
    "cnstep",   # grammar-constrained single-step decode (masked sample)
    "samprow",  # set one slot's sampling row (temp/top-k/top-p/last)
    "snap",     # replicate+fetch KV rows (preempt snapshot, migration)
    "pfxput",   # slice live rows into the device prefix cache
    "pfxdrop",  # release a device prefix entry
    "pfximp",   # materialize host bytes as a device prefix entry
    "pfxexp",   # replicate+fetch a prefix entry (fleet export)
    "poolexp",  # physical pool: replicate+fetch pool rows (fleet export)
    "cow",      # physical pool: copy-on-write one block
    "pput",     # physical pool: publish one block (arena/pool/host)
)


# ---------------------------------------------------------------------------
# Command channel: leader → followers, length-prefixed pickles over TCP
# ---------------------------------------------------------------------------


PING_INTERVAL_S = 5.0  # leader liveness beacon cadence while the queue is idle


class CmdLeader:
    """Leader side: accept one connection per follower, broadcast commands."""

    def __init__(self, bind_addr: str, n_followers: int, timeout_s: float = 60.0):
        host, _, port = bind_addr.rpartition(":")
        self._srv = socket.create_server((host or "0.0.0.0", int(port)))
        self._srv.settimeout(timeout_s)
        self.conns: list[socket.socket] = []
        # send() is called from the engine loop AND shutdown()'s thread (the
        # "stop" frame); interleaved sendall() would corrupt the frame stream
        self._send_lock = threading.Lock()
        self.last_send_t = time.monotonic()
        for _ in range(n_followers):
            c, _addr = self._srv.accept()
            c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.conns.append(c)

    def send(self, obj: Any) -> None:
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        frame = struct.pack("<I", len(blob)) + blob
        with self._send_lock:
            for c in self.conns:
                c.sendall(frame)
            self.last_send_t = time.monotonic()

    def ping_if_idle(self, interval_s: float = PING_INTERVAL_S) -> None:
        """Beacon so followers can tell a quiet leader from a dead one."""
        if time.monotonic() - self.last_send_t >= interval_s:
            self.send(("ping",))

    def close(self) -> None:
        for c in self.conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()


class CmdFollower:
    """Follower side: connect (with retry — the leader may boot later) and
    wait on recv with a liveness bound: the leader beacons ("ping") every
    PING_INTERVAL_S while idle, so a follower that sees NO bytes for
    `idle_timeout_s` concludes the leader process is dead (not merely quiet)
    and raises instead of blocking forever on a half-open socket."""

    def __init__(self, addr: str, timeout_s: float = 60.0, idle_timeout_s: float = 600.0):
        host, _, port = addr.rpartition(":")
        deadline = time.time() + timeout_s
        while True:
            try:
                self._c = socket.create_connection((host, int(port)), timeout=5.0)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        self._c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # finite so recv wakes periodically to check the liveness deadline.
        # idle_timeout_s is deliberately generous: the leader stops beaconing
        # while ITS dispatch blocks (first-admit XLA compiles can run
        # minutes), so this guards against a dead leader, not a slow one.
        self.idle_timeout_s = max(idle_timeout_s, 1.0)
        self._c.settimeout(min(PING_INTERVAL_S, self.idle_timeout_s))

    def recv(self) -> Any:
        hdr = self._recv_exact(4)
        (n,) = struct.unpack("<I", hdr)
        return pickle.loads(self._recv_exact(n))

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        deadline = time.monotonic() + self.idle_timeout_s
        while len(buf) < n:
            try:
                chunk = self._c.recv(n - len(buf))
            except TimeoutError:
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"leader sent nothing for {self.idle_timeout_s:.0f}s "
                        "(no command or ping): presumed dead"
                    ) from None
                continue
            if not chunk:
                raise ConnectionError("command channel closed")
            buf += chunk
            deadline = time.monotonic() + self.idle_timeout_s
        return buf

    def close(self) -> None:
        self._c.close()


# ---------------------------------------------------------------------------
# Backends
# ---------------------------------------------------------------------------


class DispatchBackend:
    """What a dispatch means. The engine is backend-agnostic: it calls
    `emit(op, args)` before running each op closure, `idle()` from quiet
    loop iterations, `stop()`/`close()` at shutdown, and hands its op
    registry to `run_follower(exec_table)` on non-leader processes."""

    #: True when device arrays are GLOBAL (multi-controller GSPMD): init
    #: must be born-sharded, host reads must come from replicated outputs.
    spmd: bool = False

    def start(self) -> None:  # leader-side channel setup (blocking accept)
        pass

    def emit(self, op: str, args: tuple) -> None:  # broadcast one step
        pass

    def idle(self) -> None:  # liveness beacon hook
        pass

    def run_follower(self, exec_table: Mapping[str, Callable]) -> None:
        raise RuntimeError("this backend has no follower role")

    def stop(self) -> None:  # release followers
        pass

    def close(self) -> None:
        pass


class LocalArraysBackend(DispatchBackend):
    """Single-process arrays (the classic `GenerationEngine` path).
    Every hook is a no-op: `_dx` degenerates to a direct call and the
    engine behaves byte-identically to the pre-dispatch code."""

    spmd = False


class GSPMDBackend(DispatchBackend):
    """Multi-controller leader/follower execution over one global mesh.

    The leader serializes the step-program over the command channel; each
    follower replays it through the SAME op registry the leader executes.
    No scheduling state crosses the wire — only op names and host payloads.
    """

    spmd = True

    def __init__(
        self,
        cmd_addr: str,
        *,
        connect_timeout_s: float = 60.0,
        idle_timeout_s: float = 600.0,
    ):
        self.cmd_addr = cmd_addr
        self.connect_timeout_s = connect_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self._leader: CmdLeader | None = None
        import jax  # deferred: this module stays importable without jax

        self._n_followers = max(jax.process_count() - 1, 0)

    # -- leader side --------------------------------------------------------

    def start(self) -> None:
        if self._leader is None:
            self._leader = CmdLeader(
                self.cmd_addr, self._n_followers, timeout_s=self.connect_timeout_s
            )

    def emit(self, op: str, args: tuple) -> None:
        if self._leader is not None and self._leader.conns:
            self._leader.send(("step", op, args))

    def idle(self) -> None:
        if self._leader is not None and self._leader.conns:
            self._leader.ping_if_idle()

    def stop(self) -> None:
        if self._leader is not None and self._leader.conns:
            try:
                self._leader.send(("stop",))
            except OSError:
                pass

    def close(self) -> None:
        if self._leader is not None:
            self._leader.close()
            self._leader = None

    # -- follower side ------------------------------------------------------

    def run_follower(self, exec_table: Mapping[str, Callable]) -> None:
        """Replay the leader's step-program. This loop is the ENTIRE
        follower: there is deliberately no per-op branching here — an op
        the registry does not know is a protocol error, not a feature."""
        fol = CmdFollower(
            self.cmd_addr,
            timeout_s=self.connect_timeout_s,
            idle_timeout_s=self.idle_timeout_s,
        )
        try:
            while True:
                cmd = fol.recv()
                tag = cmd[0]
                if tag == "ping":
                    continue
                if tag == "stop":
                    return
                if tag != "step":
                    raise ValueError(f"unknown dispatch frame {tag!r}")
                exec_table[cmd[1]](*cmd[2])
        finally:
            fol.close()


# ---------------------------------------------------------------------------
# 2-process demo main (the boot smoke __graft_entry__ drives): one unified
# engine, GSPMD backend, greedy tokens across the process boundary.
# ---------------------------------------------------------------------------


def _demo_main() -> int:
    n_local = int(os.environ.get("SLICE_LOCAL_DEVICES", "4"))
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_local}"
        ).strip()
    import jax

    if os.environ.get("SLICE_DEMO_CPU", "1") != "0":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ..parallel import distributed

    multi = distributed.initialize()
    spec = os.environ.get("SLICE_MESH", "dp=4,tp=2")
    mesh = distributed.make_global_mesh(spec)

    from .engine import GenerationEngine

    eng = GenerationEngine(
        os.environ.get("SLICE_MODEL", "tiny-llm"),
        mesh=mesh,
        backend=GSPMDBackend(os.environ["SLICE_CMD_ADDR"]),
        max_slots=int(os.environ.get("SLICE_SLOTS", "8")),
        max_seq_len=int(os.environ.get("SLICE_SEQ", "128")),
        dtype=jnp.float32,
        decode_chunk=4,
    )
    if jax.process_index() == 0:
        eng.start()
        out = eng.generate("dispatch dryrun", max_tokens=6, temperature=0.0)
        n_tok = out["usage"]["completion_tokens"]
        eng.shutdown()
        print(
            f"DISPATCH DEMO OK: {jax.process_count()} processes, "
            f"mesh {spec}, {n_tok} tokens",
            flush=True,
        )
    else:
        eng.run_follower()
        print("DISPATCH FOLLOWER OK", flush=True)
    return 0 if multi or jax.process_count() == 1 else 1


if __name__ == "__main__":
    raise SystemExit(_demo_main())
