"""Multi-host serving engine: ONE GSPMD data plane spanning every process
of a `jax.distributed` cluster, driven by a leader/follower command channel.

The reference's multi-host story is one schedulable device per Ollama
endpoint (`core/internal/discovery/discovery.go:266-280`) — each host serves
alone. A TPU slice is different: the MODEL spans hosts, so serving it means
every process of the slice must dispatch the same XLA program over one
global `jax.sharding.Mesh` while exactly one process talks HTTP. This module
is that per-slice device:

  - **Process 0 (leader)** owns all host-side state: the request queue, slot
    table, sampling params, stop/EOS handling, SSE emission. It exposes the
    same `generate_stream` interface `GenerationEngine` gives CoreServer, so
    the slice registers through discovery as ONE device and serves
    `/v1/chat/completions` unchanged.
  - **Processes 1..n-1 (followers)** are stateless executors: they block on
    a TCP command channel (the cluster-plane analog of the reference's
    HTTP/gRPC control plane — SURVEY.md §2.2) and mirror every dispatch.
    Commands carry the full host-side inputs (tokens, lengths, masks, RNG
    counter), so a follower needs no scheduling logic and cannot diverge:
    multi-controller JAX treats identical numpy inputs as replicated global
    arrays, and the jitted programs are identical by construction.
  - **Device state** (weights, KV cache) is born sharded: params and cache
    init run as jitted programs with explicit `out_shardings` over the
    global mesh, so no process ever materializes the full tree and a real
    checkpoint streams per-process shards (`make_array_from_callback`).

The decode round returns its sampled tokens with a REPLICATED out-sharding
(XLA inserts the all-gather across dp), so the leader fetches the full
token block locally — followers fetch nothing and stay async.

Scheduling: with `prefill_chunk > 0` long prompts prefill chunk-by-chunk
under the SAME token-budget policy as `GenerationEngine`
(executor/scheduler.py): the leader asks the shared `TokenBudgetScheduler`
for a per-iteration prefill token budget, stages one bounded chunk group,
and broadcasts it as a "chunk" command before each decode round — decode
cadence on the slice is bounded by budget arithmetic, not backlog depth.
Followers replay the dispatches and need no policy.

Scope vs `GenerationEngine`: no prompt-prefix cache / pipelined rings /
slot compaction yet — the single-host engine keeps those; this engine's
job is the cross-process data plane.
"""

from __future__ import annotations

import base64
import logging
import os
import pickle
import queue
import socket
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Iterator

from contextlib import nullcontext

import jax
import jax.numpy as jnp
import numpy as np

from ..models import (
    init_kv_cache,
    init_llama_params,
    llama_decode_step,
    llama_prefill,
)
from ..models.configs import ModelConfig, resolve_config
from ..telemetry import recorder as _flight
from ..models.llama import llama_prefill_chunk_batch
from ..ops.sampling import sample_tokens, spec_verify
from . import migration
from .common import pow2_bucket
from .drafter import NGramDrafter
from .memory import (
    KVPool,
    KVSnapshot,
    RESTORE_AGING_TTFT_MULT,
    bucket_len,
    pytree_nbytes,
)
from .paging import PagedKVManager
from .scheduler import TokenBudgetScheduler
from .tokenizer import Tokenizer, load_tokenizer

log = logging.getLogger("slice")

_DONE = object()


# ---------------------------------------------------------------------------
# Command channel: leader → followers, length-prefixed pickles over TCP
# ---------------------------------------------------------------------------


PING_INTERVAL_S = 5.0  # leader liveness beacon cadence while the queue is idle


class CmdLeader:
    """Leader side: accept one connection per follower, broadcast commands."""

    def __init__(self, bind_addr: str, n_followers: int, timeout_s: float = 60.0):
        host, _, port = bind_addr.rpartition(":")
        self._srv = socket.create_server((host or "0.0.0.0", int(port)))
        self._srv.settimeout(timeout_s)
        self.conns: list[socket.socket] = []
        # send() is called from the engine loop AND shutdown()'s thread (the
        # "stop" frame); interleaved sendall() would corrupt the frame stream
        self._send_lock = threading.Lock()
        self.last_send_t = time.monotonic()
        for _ in range(n_followers):
            c, _addr = self._srv.accept()
            c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.conns.append(c)

    def send(self, obj: Any) -> None:
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        frame = struct.pack("<I", len(blob)) + blob
        with self._send_lock:
            for c in self.conns:
                c.sendall(frame)
            self.last_send_t = time.monotonic()

    def ping_if_idle(self, interval_s: float = PING_INTERVAL_S) -> None:
        """Beacon so followers can tell a quiet leader from a dead one."""
        if time.monotonic() - self.last_send_t >= interval_s:
            self.send(("ping",))

    def close(self) -> None:
        for c in self.conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()


class CmdFollower:
    """Follower side: connect (with retry — the leader may boot later) and
    wait on recv with a liveness bound: the leader beacons ("ping") every
    PING_INTERVAL_S while idle, so a follower that sees NO bytes for
    `idle_timeout_s` concludes the leader process is dead (not merely quiet)
    and raises instead of blocking forever on a half-open socket."""

    def __init__(self, addr: str, timeout_s: float = 60.0, idle_timeout_s: float = 600.0):
        host, _, port = addr.rpartition(":")
        deadline = time.time() + timeout_s
        while True:
            try:
                self._c = socket.create_connection((host, int(port)), timeout=5.0)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        self._c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # finite so recv wakes periodically to check the liveness deadline.
        # idle_timeout_s is deliberately generous: the leader stops beaconing
        # while ITS dispatch blocks (first-admit XLA compiles can run
        # minutes), so this guards against a dead leader, not a slow one.
        self.idle_timeout_s = max(idle_timeout_s, 1.0)
        self._c.settimeout(min(PING_INTERVAL_S, self.idle_timeout_s))

    def recv(self) -> Any:
        hdr = self._recv_exact(4)
        (n,) = struct.unpack("<I", hdr)
        return pickle.loads(self._recv_exact(n))

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        deadline = time.monotonic() + self.idle_timeout_s
        while len(buf) < n:
            try:
                chunk = self._c.recv(n - len(buf))
            except TimeoutError:
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"leader sent nothing for {self.idle_timeout_s:.0f}s "
                        "(no command or ping): presumed dead"
                    ) from None
                continue
            if not chunk:
                raise ConnectionError("command channel closed")
            buf += chunk
            deadline = time.monotonic() + self.idle_timeout_s
        return buf

    def close(self) -> None:
        self._c.close()


# ---------------------------------------------------------------------------
# Requests / slots (leader-side bookkeeping)
# ---------------------------------------------------------------------------


@dataclass
class SliceRequest:
    prompt_ids: list[int]
    max_tokens: int = 256
    temperature: float = 0.7
    top_k: int = 0
    top_p: float = 1.0
    stop: list[str] = field(default_factory=list)
    # KV-pool preemption rank (memory.py): higher survives longer; only
    # read when TPU_KV_HOST_OFFLOAD is on (GenRequest parity)
    priority: int = 0
    out: "queue.Queue[Any]" = field(default_factory=queue.Queue)


@dataclass
class _Slot:
    req: SliceRequest
    prompt_len: int
    generated: int = 0
    text: str = ""
    pending: bytes = b""
    spec: Any = None  # NGramDrafter when speculation is on (leader-only)
    # KV pool victim signals (stamped only when the pool is on)
    active_at: float = 0.0
    last_emit: float = 0.0


@dataclass
class _SlicePrefill:
    """A reserved slot whose prompt is mid-way through chunked prefill on
    the slice (leader-side bookkeeping; followers just replay the "chunk"
    dispatches). The slot's length mirror is PARKED at max_seq_len while
    chunks land: decode rounds write K/V unconditionally at every row's
    length, and the out-of-bounds position drops the write instead of
    corrupting the prompt KV under construction."""

    req: SliceRequest
    ids: list[int]
    done: int = 0  # tokens already written into the cache
    t0: float = 0.0  # submit time (scheduler deadline + TTFT stat)


class SliceEngine:
    """See module docstring. Construct in EVERY process of the cluster with
    identical arguments; then `.start()` on the leader (process 0) and
    `.run_follower()` everywhere else."""

    def __init__(
        self,
        model: str | ModelConfig = "tiny-llm",
        *,
        mesh: Any,
        cmd_addr: str,
        max_slots: int = 8,
        max_seq_len: int = 256,
        dtype: Any = jnp.bfloat16,
        decode_chunk: int = 8,
        quant: str = "",
        weights_dir: str = "",
        tokenizer: Tokenizer | None = None,
        seed: int = 0,
        connect_timeout_s: float = 60.0,
        prefill_chunk: int = 0,
        target_ttft_ms: float = 2000.0,
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.quant import quantized_specs
        from ..parallel.sharding import kv_cache_specs, llama_param_specs

        self.cfg = resolve_config(model, weights_dir) if isinstance(model, str) else model
        self.mesh = mesh
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.decode_chunk = decode_chunk
        self.prefill_chunk = max(0, prefill_chunk)
        # Ragged packed prefill (GenerationEngine.ragged_prefill) stays OFF
        # on the sliced path regardless of TPU_RAGGED_PREFILL: every follower
        # replays broadcast dispatch commands by shape, and the ragged
        # descriptors assume the single-program engine's slot/ledger
        # ownership. Guarded passthrough — the bucketed chunk machinery below
        # is the multi-host path of record.
        self.ragged_prefill = False
        self.target_ttft_ms = max(1.0, float(target_ttft_ms))
        self.quant = quant
        self.tokenizer = tokenizer or load_tokenizer(weights_dir)
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.is_leader = self.process_index == 0
        self._cmd_addr = cmd_addr
        self._connect_timeout_s = connect_timeout_s
        cfg = self.cfg

        dp = mesh.shape.get("dp", 1)
        if max_slots % max(dp, 1) != 0:
            raise ValueError(f"max_slots {max_slots} must divide over dp={dp}")

        def ns(spec):
            return jax.tree.map(
                lambda s: NamedSharding(mesh, s), spec,
                is_leaf=lambda x: isinstance(x, P),
            )

        pspecs = llama_param_specs(cfg)
        if quant == "int8":
            from ..models.quant import init_llama_params_quantized

            pspecs = quantized_specs(pspecs)
            init_params = partial(
                init_llama_params_quantized, cfg, jax.random.PRNGKey(seed),
                scale_dtype=dtype,
            )
        else:
            init_params = partial(
                init_llama_params, cfg, jax.random.PRNGKey(seed), dtype=dtype
            )
        cspecs = kv_cache_specs()
        repl = NamedSharding(mesh, P())

        with mesh:
            if weights_dir:
                self.params = self._load_checkpoint_global(
                    cfg, weights_dir, dtype, mesh, ns(pspecs), quant=quant
                )
            else:
                # born sharded: the init runs as ONE GSPMD program with
                # explicit out_shardings — no process materializes the tree
                self.params = jax.jit(init_params, out_shardings=ns(pspecs))()
            cache = jax.jit(
                partial(init_kv_cache, cfg, max_slots, max_seq_len, dtype=dtype),
                out_shardings=jax.tree.map(
                    lambda s: NamedSharding(mesh, s), cspecs,
                    is_leaf=lambda x: isinstance(x, P),
                ),
            )()
        self._ck, self._cv = cache["k"], cache["v"]
        self._base_key = jax.random.PRNGKey(seed + 1)
        base_key = self._base_key

        cache_out = (
            jax.tree.map(lambda s: NamedSharding(mesh, s), cspecs["k"],
                         is_leaf=lambda x: isinstance(x, P)),
            jax.tree.map(lambda s: NamedSharding(mesh, s), cspecs["v"],
                         is_leaf=lambda x: isinstance(x, P)),
        )

        K = decode_chunk

        @partial(
            jax.jit,
            donate_argnums=(1, 2),
            out_shardings=((repl,) + cache_out),
        )
        def decode_fn(params, ck, cv, toks, lens, active, temps, topks, topps,
                      counter):
            """K chained steps + fused sampling. `toks`/`lens`/`active` and
            the sampling params arrive as identical numpy on every process
            (replicated by multi-controller semantics). Output tokens are
            REPLICATED [K, B] so the leader fetches them without a separate
            collective; inactive rows freeze (their lengths do not advance
            and their token repeats)."""

            cmd_key = jax.random.fold_in(base_key, counter)

            def step(carry, i):
                ck, cv, toks, lens = carry
                logits, ck, cv = llama_decode_step(cfg, params, ck, cv, toks, lens)
                key = jax.random.fold_in(cmd_key, i)  # i < K; admit uses K
                new = sample_tokens(logits, key, temps, topks, topps,
                                    active=active)
                new = jnp.where(active, new, toks)
                lens = lens + active.astype(jnp.int32)
                return (ck, cv, new, lens), new

            (ck, cv, _, _), out = jax.lax.scan(
                step, (ck, cv, toks, lens), jnp.arange(K)
            )
            return out, ck, cv

        kv_axes = 5  # [L, B, Hkv, S, hd]

        @partial(jax.jit, donate_argnums=(1, 2),
                 out_shardings=(cache_out + (repl,)))
        def admit_fn(params, ck, cv, tokens, lengths, slots, live_n, temps,
                     topks, topps, counter):
            """Whole-prompt batched prefill + cache insert + first-token
            sample, one dispatch (the slice analog of GenerationEngine's
            fused admit_fn). Pad rows (i >= live_n) write nothing."""
            logits, ks, vs = llama_prefill(cfg, params, tokens, lengths)

            def body(i, cc):
                ck, cv = cc

                def ins(cc):
                    ck, cv = cc
                    kr = jax.lax.dynamic_slice_in_dim(ks, i, 1, 1)
                    vr = jax.lax.dynamic_slice_in_dim(vs, i, 1, 1)
                    start = (0, slots[i]) + (0,) * (kv_axes - 2)
                    ck = jax.lax.dynamic_update_slice(ck, kr.astype(ck.dtype), start)
                    cv = jax.lax.dynamic_update_slice(cv, vr.astype(cv.dtype), start)
                    return ck, cv

                return jax.lax.cond(i < live_n, ins, lambda cc: cc, (ck, cv))

            ck, cv = jax.lax.fori_loop(0, tokens.shape[0], body, (ck, cv))
            # fold (counter, K): disjoint from decode's (counter, i<K) space
            key = jax.random.fold_in(jax.random.fold_in(base_key, counter), K)
            toks0 = sample_tokens(logits, key, temps, topks, topps,
                                  active=jnp.arange(tokens.shape[0]) < live_n)
            return ck, cv, toks0

        @partial(jax.jit, donate_argnums=(1, 2), static_argnames=("skey",),
                 out_shardings=((repl,) + cache_out))
        def chunk_fn(params, ck, cv, tokens, slots, starts, nvalid, skey):
            """One chunked-prefill group dispatch (GenerationEngine's
            prefill_chunk_fn, slice flavor): inputs arrive as identical
            numpy on every process; the boundary logits come back
            REPLICATED so the leader samples first tokens locally."""
            return llama_prefill_chunk_batch(
                cfg, params, ck, cv, tokens, slots, starts, nvalid, skey=skey
            )

        # Self-speculative decoding (engine.py policy, slice flavor): the
        # LEADER drafts host-side (NGramDrafter) and broadcasts a budgeted
        # "verify" command; followers replay the dispatch like any other.
        # The env knobs must match across processes (same contract as every
        # other constructor argument). TPU_SPEC=0 is the kill switch.
        self.spec_k = max(0, int(os.environ.get("TPU_SPEC_K", "") or 7))
        self.spec_min_ngram = max(
            1, int(os.environ.get("TPU_SPEC_MIN_NGRAM", "") or 2)
        )
        self.spec_max_ngram = max(self.spec_min_ngram, 3)
        self.spec_enabled = (
            os.environ.get("TPU_SPEC", "1") != "0" and self.spec_k > 0
        )
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_calls = 0
        self._spec_cooldown = 0
        B = max_slots

        @partial(jax.jit, donate_argnums=(1, 2), static_argnames=("skey",),
                 out_shardings=((repl, repl) + cache_out))
        def verify_fn(params, ck, cv, tokens, slots, starts, nvalid,
                      drafts, ndraft, temps, topks, topps, counter, skey):
            """Speculative verify: ONE chunk pass over [token, draft_1..
            draft_K] per slot with full-position logits, then accept/reject
            + the follow-on sample on device (spec_verify). (n_acc, final)
            come back REPLICATED so the leader reads them locally; pad rows
            carry slot id B (writes drop out of bounds, and `active`
            excludes them from the sampler's homogeneity reductions)."""
            logits, ck, cv = llama_prefill_chunk_batch(
                cfg, params, ck, cv, tokens, slots, starts, nvalid,
                skey=skey, all_logits=True,
            )  # [A, C, V]
            rng = jax.random.fold_in(base_key, counter)
            n_acc, final = spec_verify(
                logits, drafts, ndraft, rng, temps, topks, topps,
                active=slots < B,
            )
            return n_acc, final, ck, cv

        # KV pool preempt/restore (memory.py), mirrored as leader commands.
        # Both jits are built in EVERY process (identical by the same
        # contract as every other constructor argument) and trace lazily —
        # a slice that never preempts compiles neither.

        @partial(jax.jit, static_argnames=("bucket",),
                 out_shardings=(repl, repl))
        def snapshot_fn(ck, cv, slot, bucket):
            """A slot's committed KV rows [0, bucket), REPLICATED so every
            process device_gets its own full host copy (the restore command
            then ships only (slot, snap_id) — no KV over the channel). No
            donation: the cache stays live for the next round."""

            def cut(c):
                return jax.lax.dynamic_slice(
                    c, (0, slot, 0, 0, 0),
                    (c.shape[0], 1, c.shape[2], bucket, c.shape[4]),
                )

            return cut(ck), cut(cv)

        @partial(jax.jit, donate_argnums=(0, 1), out_shardings=cache_out)
        def restore_fn(ck, cv, pk, pv, slot):
            """Write a snapshot's rows back into `slot` (the admit insert
            path, single-row flavor). Writing the full pow2 bucket is exact:
            rows past the committed length are dead and the first
            post-restore decode round overwrites position `length` before
            any read attends there."""
            start = (0, slot, 0, 0, 0)
            ck = jax.lax.dynamic_update_slice(ck, pk.astype(ck.dtype), start)
            cv = jax.lax.dynamic_update_slice(cv, pv.astype(cv.dtype), start)
            return ck, cv

        self._decode_fn = decode_fn
        self._admit_fn = admit_fn
        self._chunk_fn = chunk_fn
        self._verify_fn = verify_fn
        self._snapshot_fn = snapshot_fn
        self._restore_fn = restore_fn
        # per-process host copies of offloaded rows, keyed by snap_id (the
        # follower side of the mirrored preempt/restore commands; the leader
        # keeps its copy here too)
        self._snaps: dict[int, tuple[Any, Any]] = {}
        self._snap_ctr = 0
        # Leader-side admission/preemption policy: same KVPool as
        # GenerationEngine. TPU_KV_HOST_OFFLOAD=0 (default) never
        # constructs it — the leader loop's pool hooks are all guarded.
        self._pool: KVPool | None = None
        if os.environ.get("TPU_KV_HOST_OFFLOAD", "0") not in ("", "0", "false", "no", "off"):
            self._pool = KVPool(
                max_slots=max_slots,
                max_seq_len=max_seq_len,
                bytes_per_slot=pytree_nbytes({"k": self._ck, "v": self._cv})
                // max(1, max_slots),
                watermark=float(os.environ.get("TPU_ADMIT_WATERMARK", "") or 1.5),
                policy=os.environ.get("TPU_PREEMPT_POLICY", "") or "priority",
            )

        # KV migration inbox (executor/migration.py): a slice can serve as
        # a decode-role TARGET — payloads land here from migrate_import
        # (any thread) and the leader loop restores them into free slots.
        # Unlike pool restore, followers never saw this KV, so the mirrored
        # "migin" command ships the rows themselves. TPU_MIGRATE=0 keeps
        # the inbox None and no migration codepath runs.
        self._migrate_in: "queue.Queue[tuple] | None" = None
        self.migrated_in_total = 0
        self.migrate_in_bytes_total = 0
        if os.environ.get("TPU_MIGRATE", "0") not in ("", "0", "false", "no", "off"):
            self._migrate_in = queue.Queue()

        # Paged-KV ledger (executor/paging.py): constructed in EVERY process
        # from the same constructor arguments, so the follower mirror starts
        # identical. The leader buffers every mutator's op list and flushes
        # one ("blk", ops) command per loop iteration — ops carry block ids,
        # never KV bytes — and followers replay them via apply_ops. The
        # slice has no prefix cache, so the prefix partition is zero and
        # every admission allocates private blocks.
        #
        # Physical paged KV (executor/physical.py): NOT constructed here,
        # deliberately. With prefix_budget_bytes=0 nothing is ever shared,
        # so every slot's block table would be the identity map — the
        # engine's block-indirect gather reduces to exactly the contiguous
        # read this slice already performs, and the mirror's op stream
        # ("pin"/"cow" replay below) stays forward-compatible if a future
        # slice grows a prefix partition. Keeping the pool out keeps the
        # multi-host dispatch trace bit-identical to pre-physical engines.
        self._paging = PagedKVManager(
            max_slots=max_slots,
            max_seq_len=max_seq_len,
            bytes_per_token=pytree_nbytes({"k": self._ck, "v": self._cv})
            // max(1, max_slots * max_seq_len),
            prefix_budget_bytes=0,
        )
        self._blk_ops: list[tuple] = []

        # leader-side bookkeeping
        self._queue: "queue.Queue[Any]" = queue.Queue()
        self._slots: list[_Slot | None] = [None] * max_slots
        self._toks = np.zeros(max_slots, np.int32)
        self._lens = np.zeros(max_slots, np.int32)
        self._temps = np.zeros(max_slots, np.float32)
        self._topks = np.zeros(max_slots, np.int32)
        self._topps = np.ones(max_slots, np.float32)
        self._counter = 0
        # chunked-prefill reservations (leader-only; see _SlicePrefill) and
        # the shared token-budget policy (executor/scheduler.py) — the SAME
        # object GenerationEngine uses, so single-host and slice serving
        # make identical scheduling decisions
        self._prefills: dict[int, _SlicePrefill] = {}
        self._prefill_q: deque[int] = deque()
        self._sched = TokenBudgetScheduler(
            target_ttft_ms=self.target_ttft_ms,
            min_budget=min(64, self.prefill_chunk) if self.prefill_chunk else 1,
        )
        # Flight recorder + compile ledger (telemetry/recorder.py): leader
        # methods record dispatch events and first-sighting compile walls
        # into the SAME process-wide singletons GenerationEngine feeds —
        # followers construct the references but never call them (all hooks
        # live in leader-only methods).
        self._flight = _flight.get_recorder()
        self._ledger = _flight.get_compile_ledger()
        self._seen_exec_shapes: set[tuple] = set()
        self._shutdown = threading.Event()
        self._thread: threading.Thread | None = None
        self._leader_ch: CmdLeader | None = None
        self.total_tokens = 0
        self.total_requests = 0
        self.total_errors = 0
        self._ttfts: deque[float] = deque(maxlen=512)
        self._tps_marks: deque[tuple[float, int]] = deque(maxlen=256)
        self.attn_impl = "xla"
        self.dead: str = ""  # non-empty = engine loop died with this error
        self._dead_lock = threading.Lock()  # atomizes submit vs shutdown drain

    # -- checkpoint -------------------------------------------------------

    @staticmethod
    def _load_checkpoint_global(cfg, ckpt_dir, dtype, mesh, shardings, quant: str = ""):
        """Every process reads the safetensors dir (standard multi-host
        practice) and contributes ONLY its addressable shards via
        make_array_from_callback — the full tree is never resident per
        process beyond the mmap'd host file."""
        from ..models.weights import hf_to_llama_params, read_checkpoint_dir

        host = hf_to_llama_params(cfg, read_checkpoint_dir(ckpt_dir))
        if quant == "int8":
            from ..models.quant import quantize_params

            # quantize the host tree BEFORE placement so its structure matches
            # the quantized PartitionSpecs; pin the work to the CPU backend —
            # the tree must stay host-resident until make_array_from_callback
            # streams per-process shards
            try:
                cpu = jax.local_devices(backend="cpu")[0]
            except RuntimeError:
                cpu = None
            with jax.default_device(cpu) if cpu is not None else nullcontext():
                host = quantize_params(host)
        elif quant:
            raise NotImplementedError(
                f"slice engine quant={quant!r} with a checkpoint (only 'int8' is supported)"
            )

        def up(arr, sharding):
            a = np.asarray(arr)
            # int8 payloads must keep their dtype; only float leaves
            # (weights, scales, norms) follow the engine compute dtype
            if dtype is not None and np.issubdtype(a.dtype, np.floating):
                a = a.astype(dtype)
            return jax.make_array_from_callback(
                a.shape, sharding, lambda idx: a[idx]
            )

        return jax.tree.map(up, host, shardings)

    # -- follower ---------------------------------------------------------

    def run_follower(self) -> None:
        """Blocking command loop; returns on the leader's stop command."""
        assert not self.is_leader
        ch = CmdFollower(self._cmd_addr, timeout_s=self._connect_timeout_s)
        try:
            while True:
                cmd = ch.recv()
                op = cmd[0]
                if op == "ping":  # leader liveness beacon, no work
                    continue
                if op == "stop":
                    return
                if op == "admit":
                    _, tokens, lengths, slots, live_n, temps, topks, topps, ctr = cmd
                    with self.mesh:
                        self._ck, self._cv, _ = self._admit_fn(
                            self.params, self._ck, self._cv, tokens, lengths,
                            slots, live_n, temps, topks, topps, ctr,
                        )
                elif op == "decode":
                    _, toks, lens, active, temps, topks, topps, ctr = cmd
                    with self.mesh:
                        _, self._ck, self._cv = self._decode_fn(
                            self.params, self._ck, self._cv, toks, lens,
                            active, temps, topks, topps, ctr,
                        )
                elif op == "chunk":
                    # budget-bounded chunked-prefill group (token-budget
                    # scheduler); the leader samples from the logits, a
                    # follower only needs the cache writes
                    _, tokens, slots, starts, nvalid, skey = cmd
                    with self.mesh:
                        _, self._ck, self._cv = self._chunk_fn(
                            self.params, self._ck, self._cv, tokens,
                            slots, starts, nvalid, int(skey),
                        )
                elif op == "verify":
                    # budgeted speculative verify round: replay the dispatch
                    # for the cache writes; (n_acc, final) are replicated and
                    # only the leader consumes them
                    (_, tokens, slots, starts, nvalid, drafts, ndraft,
                     temps, topks, topps, ctr, skey) = cmd
                    with self.mesh:
                        _, _, self._ck, self._cv = self._verify_fn(
                            self.params, self._ck, self._cv, tokens, slots,
                            starts, nvalid, drafts, ndraft, temps, topks,
                            topps, ctr, int(skey),
                        )
                elif op == "preempt":
                    # KV-pool offload: slice the victim's committed rows
                    # (replicated) and keep a HOST copy keyed by snap_id —
                    # the matching "restore" ships no KV payload
                    _, slot, bucket, snap_id = cmd
                    with self.mesh:
                        kr, vr = self._snapshot_fn(
                            self._ck, self._cv, np.int32(slot), int(bucket)
                        )
                    self._snaps[int(snap_id)] = (
                        jax.device_get(kr), jax.device_get(vr)
                    )
                elif op == "restore":
                    _, slot, snap_id = cmd
                    kr, vr = self._snaps.pop(int(snap_id))
                    with self.mesh:
                        self._ck, self._cv = self._restore_fn(
                            self._ck, self._cv, kr, vr, np.int32(slot)
                        )
                elif op == "migin":
                    # migrated-in KV: the rows were computed on ANOTHER
                    # engine, so no local host copy exists — the command
                    # carries them (the only data-plane command that ships
                    # KV bytes over the channel)
                    _, slot, kr, vr = cmd
                    with self.mesh:
                        self._ck, self._cv = self._restore_fn(
                            self._ck, self._cv, kr, vr, np.int32(slot)
                        )
                elif op == "blk":
                    # mirrored paging-ledger mutations: block ids only, no
                    # KV bytes — replayed so every process can answer block
                    # economy queries and audit for leaks identically
                    self._paging.apply_ops(cmd[1])
                else:  # pragma: no cover
                    raise ValueError(f"unknown slice command {op!r}")
        finally:
            ch.close()

    # -- leader -----------------------------------------------------------

    def start(self) -> "SliceEngine":
        assert self.is_leader, "start() is leader-only; followers run_follower()"
        self._leader_ch = CmdLeader(
            self._cmd_addr, self.process_count - 1,
            timeout_s=self._connect_timeout_s,
        )
        self._thread = threading.Thread(
            target=self._engine_loop, name="slice-engine", daemon=True
        )
        self._thread.start()
        return self

    def submit(self, req: SliceRequest) -> None:
        # the dead-check and the put must be atomic against shutdown()'s
        # queue drain: a submit that passed the check pre-drain would
        # otherwise land in a dead queue and hang its consumer forever
        with self._dead_lock:
            if self.dead:
                req.out.put({"type": "error", "error": f"engine dead: {self.dead}"})
                req.out.put(_DONE)
                return
            self._queue.put(req)

    def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 256,
        temperature: float = 0.7,
        top_k: int = 0,
        top_p: float = 1.0,
        stop: list[str] | None = None,
        priority: int = 0,
    ) -> Iterator[dict[str, Any]]:
        ids = self.tokenizer.encode(prompt)
        req = SliceRequest(
            prompt_ids=ids, max_tokens=max_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, stop=stop or [], priority=priority,
        )
        req._t0 = time.time()  # type: ignore[attr-defined]
        self.submit(req)
        while True:
            evt = req.out.get()
            if evt is _DONE:
                return
            yield evt
            if evt.get("type") in ("done", "error"):
                return

    def generate(self, prompt: str, **kw: Any) -> dict[str, Any]:
        parts: list[str] = []
        final: dict[str, Any] = {}
        for evt in self.generate_stream(prompt, **kw):
            if evt["type"] == "token":
                parts.append(evt["text"])
            elif evt["type"] == "done":
                final = evt
            elif evt["type"] == "error":
                raise RuntimeError(evt.get("error", "generation failed"))
        return {
            "text": "".join(parts),
            "usage": final.get("usage", {}),
            "finish_reason": final.get("finish_reason", "stop"),
        }

    # CoreServer dashboard interface (GenerationEngine parity)
    decode_compact = "off"  # compaction is a single-host engine feature
    stalled = False

    def slots_in_use(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def current_tps(self) -> float:
        now = time.time()
        window = [(t, n) for t, n in self._tps_marks if now - t <= 10.0]
        return sum(n for _, n in window) / 10.0 if window else 0.0

    def prefix_cache_stats(self) -> dict[str, Any]:
        return {"enabled": False}

    def phase_budget(self) -> dict[str, float]:
        return {}  # per-phase accounting is a single-host engine feature

    def scheduler_stats(self) -> dict[str, float]:
        """Token-budget scheduler observability (GenerationEngine parity)."""
        out = self._sched.stats()
        out["decode_batch_occupancy"] = (
            self.slots_in_use() / self.max_slots if self.max_slots else 0.0
        )
        return out

    def speculation_stats(self) -> dict[str, float]:
        """Self-speculative decoding observability (GenerationEngine
        parity — see engine.speculation_stats)."""
        drafted = float(self.spec_drafted)
        calls = float(self.spec_calls)
        return {
            "enabled": 1.0 if self.spec_enabled else 0.0,
            "k": float(self.spec_k),
            "min_ngram": float(self.spec_min_ngram),
            "drafted_tokens": drafted,
            "accepted_tokens": float(self.spec_accepted),
            "emitted_tokens": float(self.spec_emitted),
            "verify_calls": calls,
            "accept_rate": (self.spec_accepted / drafted) if drafted else 0.0,
            "tok_per_call": (self.spec_emitted / calls) if calls else 0.0,
        }

    def _offered_load(self) -> float:
        """Offered load in slot-equivalents. With the pool on, this is the
        paging ledger's unique-block accounting (engine.py parity): live
        tables and parked snapshot pins count once, plus committed decode
        growth, snapshot restore needs, and the EMA-priced admit queue."""
        queued = self._queue.qsize()
        if self._pool is None:
            return float(self.slots_in_use() + len(self._prefills) + queued)
        mgr = self._paging
        K = self.decode_chunk
        wants: dict[int, int] = {}
        for b, s in enumerate(self._slots):
            if s is None:
                continue
            rem = max(0, s.req.max_tokens - s.generated)
            wants[b] = min(int(self._lens[b]) + rem + K, self.max_seq_len)
        for slot, st in list(self._prefills.items()):
            wants[slot] = min(
                len(st.ids) + max(0, st.req.max_tokens) + K, self.max_seq_len
            )
        return mgr.offered_blocks(wants, queued) / max(1, mgr.blocks_per_slot)

    def paging_stats(self) -> dict[str, float]:
        """Paged-KV block economy (GenerationEngine parity — engines_info
        paging block, dashboard, llmtpu_kv_block* metrics)."""
        out = self._paging.stats()
        out["enabled"] = 1.0
        out["leaks"] = float(self._paging.leak_count())
        return out

    def memory_stats(self) -> dict[str, float]:
        """KV pool observability (GenerationEngine parity)."""
        pool = self._pool
        if pool is None:
            return {"enabled": 0.0}
        out = pool.stats()
        out["enabled"] = 1.0
        offered = self._offered_load()
        out["offered"] = float(offered)
        out["headroom"] = pool.headroom(offered)
        return out

    def admission_state(self) -> tuple[bool, float]:
        """(shed, retry_after_s) — side-effect free (GenerationEngine
        parity; see engine.admission_state)."""
        pool = self._pool
        if pool is None:
            return False, 0.0
        offered = self._offered_load()
        if pool.admit_ok(offered):
            return False, 0.0
        mean_tokens = (
            self.total_tokens / self.total_requests if self.total_requests else 64.0
        )
        n_waiting = self._queue.qsize() + pool.preempted_count()
        retry = self._sched.drain_estimate_s(
            max(1, n_waiting), mean_tokens, self.decode_chunk, self.max_slots
        )
        return True, min(600.0, max(1.0, retry))

    def note_shed(self, n: int = 1) -> None:
        if self._pool is not None:
            self._pool.note_shed(n)

    def ttft_percentiles(self) -> tuple[float, float, int]:
        if not self._ttfts:
            return 0.0, 0.0, 0
        xs = sorted(self._ttfts)
        return (
            xs[len(xs) // 2],
            xs[min(len(xs) - 1, int(len(xs) * 0.95))],
            len(xs),
        )

    def shutdown(self) -> None:
        with self._dead_lock:
            if not self.dead:
                self.dead = "engine shut down"  # submit() rejects from here on
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        # drain: active slots and queued requests must get terminal events —
        # an SSE handler blocked in req.out.get() would otherwise hang the
        # server's shutdown forever (GenerationEngine.shutdown parity). The
        # drain runs under the same lock as submit's dead-check+put, so no
        # request can slip into the queue after it.
        with self._dead_lock:
            self._drain_requests("engine shut down")
        if self._leader_ch is not None:
            try:
                self._leader_ch.send(("stop",))
            except OSError:
                pass
            self._leader_ch.close()

    # -- engine loop ------------------------------------------------------

    def _free_slots(self) -> list[int]:
        # mid-prefill reservations are neither free nor decodable
        return [
            i for i, s in enumerate(self._slots)
            if s is None and i not in self._prefills
        ]

    # -- KV pool: preemption with host offload (leader-side policy) --------

    def _aging_s(self) -> float:
        return RESTORE_AGING_TTFT_MULT * self.target_ttft_ms / 1000.0

    def _peek_queue_head(self) -> SliceRequest | None:
        # the leader loop is the queue's only consumer, so peeking is stable
        try:
            return self._queue.queue[0]
        except IndexError:
            return None

    def _maybe_preempt(self) -> bool:
        """At most one eviction per loop iteration, mirrored as a "preempt"
        command: every process slices the victim's committed rows and keeps
        its own host copy under snap_id. The loop is fully synchronous, so
        _lens/_toks are committed-exact — no pipeline drain needed (the
        single-host engine's extra step)."""
        pool = self._pool
        if self._queue.empty() or not pool.may_preempt():
            return False
        live = [
            (b, s) for b, s in enumerate(self._slots) if s is not None
        ]
        if not live or self._free_slots():
            return False
        head = self._peek_queue_head()
        if head is None:
            return False
        min_pri = min(s.req.priority for _, s in live)
        head_t0 = getattr(head, "_t0", None)
        aged = head_t0 is not None and time.time() - head_t0 > self._aging_s()
        if head.priority <= min_pri and not aged:
            return False
        victim = pool.pick_victim([
            {
                "slot": b,
                "priority": s.req.priority,
                "last_activity": s.last_emit or s.active_at,
                "tokens_remaining": max(0, s.req.max_tokens - s.generated),
            }
            for b, s in live
        ])
        if victim is None:
            return False
        b = victim["slot"]
        s = self._slots[b]
        L = int(self._lens[b])
        Lb = bucket_len(L, self.max_seq_len)
        snap_id = self._snap_ctr
        self._snap_ctr += 1
        t0 = time.perf_counter()
        cmd = ("preempt", np.int32(b), np.int32(Lb), np.int32(snap_id))
        if self._leader_ch is not None:
            self._leader_ch.send(cmd)
        with self.mesh:
            kr, vr = self._snapshot_fn(
                self._ck, self._cv, np.int32(b), int(Lb)
            )
        rows = (jax.device_get(kr), jax.device_get(vr))
        dt = time.perf_counter() - t0
        self._snaps[snap_id] = rows
        snap = KVSnapshot(
            req_id="",
            priority=s.req.priority,
            length=L,
            bucket=Lb,
            last_tok=int(self._toks[b]),
            temperature=float(self._temps[b]),
            top_k=int(self._topks[b]),
            top_p=float(self._topps[b]),
            k_rows=None,  # rows live in _snaps[snap_id] on EVERY process
            v_rows=None,
            nbytes=pytree_nbytes(rows[0]) + pytree_nbytes(rows[1]),
            preempted_at=time.time(),
            slot_obj=s,
            snap_id=snap_id,
        )
        pool.offload(snap, dt)
        # park the ledger's view under snap_id (no shared pins on the slice
        # — the whole table is private and its rows are in the snapshot)
        self._blk_ops += self._paging.preempt_slot(b, snap_id)
        # release the slot WITHOUT terminal events (the request is
        # suspended); the stale length mirror is harmless — decode rounds
        # exclude the row via active0, and restore rewrites the rows
        self._slots[b] = None
        log.info(
            "slice preempted slot %d (%d tokens, %.1f MB, snap %d)",
            b, L, snap.nbytes / (1 << 20), snap_id,
        )
        return True

    def _maybe_restore(self) -> bool:
        """Restore at most one offloaded snapshot into a free slot,
        mirrored as a "restore" command carrying only (slot, snap_id)."""
        pool = self._pool
        if not pool.has_preempted():
            return False
        free = self._free_slots()
        if not free:
            return False
        snap = pool.pop_restore()
        if snap is None:
            return False
        s = snap.slot_obj
        head = self._peek_queue_head()
        aged = time.time() - snap.preempted_at > self._aging_s()
        if head is not None and head.priority >= snap.priority and not aged:
            pool.requeue(snap)
            return False
        b = free[0]
        t0 = time.perf_counter()
        cmd = ("restore", np.int32(b), np.int32(snap.snap_id))
        if self._leader_ch is not None:
            self._leader_ch.send(cmd)
        kr, vr = self._snaps.pop(snap.snap_id)
        with self.mesh:
            self._ck, self._cv = self._restore_fn(
                self._ck, self._cv, kr, vr, np.int32(b)
            )
        self._slots[b] = s
        self._toks[b] = snap.last_tok
        self._lens[b] = snap.length
        self._temps[b] = snap.temperature
        self._topks[b] = snap.top_k
        self._topps[b] = snap.top_p
        self._blk_ops += self._paging.restore_slot(b, snap.snap_id, snap.length)
        pool.note_restored(snap, time.perf_counter() - t0)
        log.info(
            "slice restored snap %d into slot %d (%d tokens) after %.1f s",
            snap.snap_id, b, snap.length, time.time() - snap.preempted_at,
        )
        return True

    # -- KV migration: decode-role import (executor/migration.py) ----------

    def migrate_import(self, payload: bytes, out: Any = None) -> SliceRequest:
        """Accept a migration payload from another engine; the leader loop
        restores it into a free slot and decode resumes at the snapshot's
        length. Callable from any thread (coordinator tick, rpc transfer
        handler). The slice has no prefix cache, so shared-prefix payloads
        always fold their fallback rows into a whole-bucket snapshot."""
        if self._migrate_in is None:
            raise RuntimeError("migration disabled (TPU_MIGRATE=0)")
        header, snap = migration.wire_to_snapshot(payload)
        if snap.shared_len:
            migration.flatten_to_whole_bucket(snap)
        if isinstance(snap.k_rows, dict) or isinstance(snap.v_rows, dict):
            raise ValueError(
                "slice engine migration supports bare-array KV only "
                "(no kv_quant payloads)"
            )
        if snap.bucket > self.max_seq_len:
            raise ValueError(
                f"snapshot bucket {snap.bucket} exceeds max_seq_len {self.max_seq_len}"
            )
        req = SliceRequest(
            prompt_ids=[int(t) for t in header.get("prompt_ids", [])],
            max_tokens=int(header["max_tokens"]),
            temperature=float(header["temperature"]),
            top_k=int(header["top_k"]),
            top_p=float(header["top_p"]),
            stop=list(header.get("stop", [])),
            priority=int(header.get("priority", 0)),
        )
        if out is not None:
            req.out = out
        now = time.time()
        s = _Slot(
            req=req,
            prompt_len=int(header["prompt_len"]),
            generated=int(header["generated"]),
            text=header.get("text", ""),
            pending=base64.b64decode(header.get("pending_b64", "")),
            active_at=now,
            last_emit=now,
        )
        snap.slot_obj = s
        with self._dead_lock:
            if self.dead:
                raise RuntimeError(f"engine dead: {self.dead}")
            self._migrate_in.put((snap, header, len(payload), s))
        return req

    def _migrate_restore_pending(self) -> bool:
        """Leader loop: restore at most the free-slot count of migrated-in
        snapshots, shipping the rows to followers via "migin"."""
        did = False
        while self._migrate_in is not None and not self._migrate_in.empty():
            free = self._free_slots()
            if not free:
                break
            try:
                snap, _header, nbytes, s = self._migrate_in.get_nowait()
            except queue.Empty:
                break
            b = free[0]
            kr, vr = snap.k_rows, snap.v_rows
            if self._leader_ch is not None:
                self._leader_ch.send(("migin", np.int32(b), kr, vr))
            with self.mesh:
                self._ck, self._cv = self._restore_fn(
                    self._ck, self._cv, kr, vr, np.int32(b)
                )
            self._slots[b] = s
            self._toks[b] = snap.last_tok
            self._lens[b] = snap.length
            self._temps[b] = snap.temperature
            self._topks[b] = snap.top_k
            self._topps[b] = snap.top_p
            # unknown snap_id → the ledger charges a fresh private table
            self._blk_ops += self._paging.restore_slot(b, -1, snap.length)
            self.total_requests += 1
            self.migrated_in_total += 1
            self.migrate_in_bytes_total += nbytes
            did = True
            log.info(
                "slice imported migrated snapshot into slot %d (%d tokens, %.1f KB)",
                b, snap.length, nbytes / 1024,
            )
        return did

    def migration_stats(self) -> dict[str, float]:
        if self._migrate_in is None:
            return {"enabled": 0.0}
        return {
            "enabled": 1.0,
            "migrated_out_total": 0.0,  # slices are import-only targets
            "migrated_in_total": float(self.migrated_in_total),
            "migrate_out_bytes_total": 0.0,
            "migrate_in_bytes_total": float(self.migrate_in_bytes_total),
            "outbox_depth": 0.0,
            "inbox_depth": float(self._migrate_in.qsize()),
        }

    def _drain_requests(self, msg: str) -> None:
        """Fail every active slot, mid-prefill reservation, and queued
        request with a terminal event. Caller holds _dead_lock (both the
        shutdown and crash paths — one copy, so the two drains cannot drift
        apart)."""
        for b in range(self.max_slots):
            s = self._slots[b]
            if s is not None:
                s.req.out.put({"type": "error", "error": msg})
                s.req.out.put(_DONE)
                self._slots[b] = None
            self._paging.free_slot(b)  # ops discarded: the mirror is dying too
        for slot, st in self._prefills.items():
            st.req.out.put({"type": "error", "error": msg})
            st.req.out.put(_DONE)
            self._paging.free_slot(slot)
        self._prefills.clear()
        self._prefill_q.clear()
        if self._pool is not None:
            # preempted-and-offloaded requests wait on a restore that will
            # never come — their consumers must not hang either
            for snap in self._pool.drain():
                self._paging.drop_snap(snap.snap_id)
                s = snap.slot_obj
                if s is not None:
                    s.req.out.put({"type": "error", "error": msg})
                    s.req.out.put(_DONE)
            self._snaps.clear()
        self._blk_ops.clear()
        while self._migrate_in is not None and not self._migrate_in.empty():
            try:
                _snap, _header, _nb, s = self._migrate_in.get_nowait()
            except queue.Empty:
                break
            s.req.out.put({"type": "error", "error": msg})
            s.req.out.put(_DONE)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.out.put({"type": "error", "error": msg})
            req.out.put(_DONE)

    def _engine_loop(self) -> None:
        try:
            while not self._shutdown.is_set():
                pooled = False
                if self._pool is not None:
                    # budgeted: at most ONE restore then ONE preempt per
                    # iteration, mirrored to followers as commands — pool
                    # traffic never crowds out the decode cadence
                    pooled = self._maybe_restore()
                migrated = self._migrate_restore_pending()
                admitted = self._try_admit()
                if self._pool is not None and self._maybe_preempt():
                    pooled = True
                # stage speculation FIRST so its chunk positions can be
                # reserved out of this iteration's prefill token budget
                # (verify rides the same chunk machinery as prompt chunks)
                spec_entries = self._stage_spec()
                reserved = (
                    sum(1 + len(d) for _, d in spec_entries)
                    if spec_entries else 0
                )
                # one budget-bounded chunk group per iteration BEFORE the
                # decode round: the token-budget scheduler caps the group so
                # in-flight streams' cadence stays within ~2x pure decode
                prefilled = self._try_prefill(reserved_tokens=reserved)
                if spec_entries:
                    decoded = self._try_verify(spec_entries)
                else:
                    decoded = self._try_decode()
                self._flush_blk_ops()
                if not (admitted or prefilled or decoded or pooled or migrated):
                    if self._leader_ch is not None:
                        self._leader_ch.ping_if_idle()
                    time.sleep(0.002)
        except Exception as e:
            # The donated KV buffers died with the failed dispatch, so this
            # engine cannot recover: mark it dead (submit() rejects from now
            # on), fail every active AND queued request loudly, and release
            # the followers — they must not block on recv() forever.
            log.exception("slice engine loop died")
            self.total_errors += 1
            with self._dead_lock:  # same atomicity as shutdown's drain
                self.dead = repr(e)
                self._drain_requests(repr(e))
            if self._leader_ch is not None:
                try:
                    self._leader_ch.send(("stop",))
                except OSError:
                    pass

    def _flush_blk_ops(self) -> None:
        """Broadcast this iteration's buffered paging-ledger mutations as
        ONE compact ("blk", ops) command. The single TCP stream preserves
        order against the data-plane commands; the ledger is metadata only,
        so relative timing vs. the KV dispatches doesn't matter."""
        ops, self._blk_ops = self._blk_ops, []
        if ops and self._leader_ch is not None:
            self._leader_ch.send(("blk", ops))

    def _note_shape(self, *key) -> bool:
        """First sighting of a dispatch shape on this slice: the first call
        of a shape pays jit trace + compile synchronously, so its wall IS
        the compile time (GenerationEngine._note_exec_shape contract)."""
        if key in self._seen_exec_shapes:
            return False
        self._seen_exec_shapes.add(key)
        return True

    def _compile_obs(self, phase: str, key: tuple, wall_s: float) -> None:
        ks = ":".join(str(p) for p in key)
        e = self._ledger.observe(phase, ks, wall_s)
        self._flight.event(
            "compile", phase=phase, key=ks,
            wall_ms=round(wall_s * 1000.0, 3), hit=e["hit"],
        )

    def _try_admit(self) -> bool:
        free = self._free_slots()
        if not free:
            return False
        pulled: list[SliceRequest] = []
        while len(pulled) < len(free):
            try:
                pulled.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if not pulled:
            return False
        self.total_requests += len(pulled)
        free_q = deque(free)
        batch: list[tuple[int, SliceRequest, list[int]]] = []
        reserved = False
        for r in pulled:
            # keep the TAIL of over-long prompts (the latest context is what
            # matters in chat — same policy as GenerationEngine), and
            # reserve a full decode round of KV headroom past the prompt
            limit = max(self.max_seq_len - self.decode_chunk - 1, 1)
            ids = r.prompt_ids[-limit:] or [0]
            slot = free_q.popleft()
            if self.prefill_chunk and len(ids) > self.prefill_chunk:
                # long prompt: reserve the slot; chunks ride the token-budget
                # scheduler (_try_prefill). PARK the length mirror at S so
                # decode rounds' unconditional K/V writes drop out-of-bounds
                # instead of landing inside the prompt KV under construction.
                self._prefills[slot] = _SlicePrefill(
                    req=r, ids=list(ids),
                    t0=getattr(r, "_t0", None) or time.time(),
                )
                self._prefill_q.append(slot)
                self._lens[slot] = self.max_seq_len
                self._blk_ops += self._paging.admit_slot(slot, len(ids))
                reserved = True
                continue
            batch.append((slot, r, ids))
        if not batch:
            return reserved
        A = len(batch)
        maxlen = max(len(ids) for _, _, ids in batch)
        bucket = pow2_bucket(min(maxlen, self.max_seq_len - 1), self.max_seq_len)
        tokens = np.zeros((A, bucket), np.int32)
        lengths = np.zeros(A, np.int32)
        slots = np.zeros(A, np.int32)
        temps = np.zeros(A, np.float32)
        topks = np.zeros(A, np.int32)
        topps = np.ones(A, np.float32)
        for i, (slot, r, ids) in enumerate(batch):
            tokens[i, : len(ids)] = ids
            lengths[i] = len(ids)
            slots[i] = slot
            temps[i] = r.temperature
            topks[i] = r.top_k
            topps[i] = r.top_p
        ctr = self._counter
        self._counter += 1
        cmd = ("admit", tokens, lengths, slots, np.int32(A), temps, topks,
               topps, np.int32(ctr))
        first = self._note_shape("admit", A, bucket)
        t0c = time.perf_counter()
        try:
            if self._leader_ch is not None:
                self._leader_ch.send(cmd)
            with self.mesh:
                self._ck, self._cv, toks0 = self._admit_fn(
                    self.params, self._ck, self._cv, tokens, lengths, slots,
                    np.int32(A), temps, topks, topps, np.int32(ctr),
                )
            toks0 = np.asarray(toks0)
            if first:
                self._compile_obs("admit", (A, bucket), time.perf_counter() - t0c)
        except Exception as e:
            # these requests were already popped off the queue — the loop's
            # crash handler can no longer see them, so fail them HERE or
            # their consumers block in out.get() forever
            for _, r, _ in batch:
                r.out.put({"type": "error", "error": repr(e)})
                r.out.put(_DONE)
            raise
        now = time.time()
        mgr = self._paging
        for i, (b, r, ids) in enumerate(batch):
            self._blk_ops += mgr.admit_slot(b, len(ids))
            want = min(
                len(ids) + max(0, r.max_tokens) + self.decode_chunk,
                self.max_seq_len,
            )
            mgr.note_admit_cost(mgr.blocks_for(want))
            slot = _Slot(req=r, prompt_len=int(lengths[i]), active_at=now)
            if self.spec_enabled:
                # seed the drafter with the prompt BEFORE the first emit so
                # tok0 lands on top of the prompt history
                slot.spec = NGramDrafter(self.spec_min_ngram, self.spec_max_ngram)
                slot.spec.extend(ids)
            self._slots[b] = slot
            self._toks[b] = toks0[i]
            self._lens[b] = lengths[i]
            self._temps[b] = r.temperature
            self._topks[b] = r.top_k
            self._topps[b] = r.top_p
            t0 = getattr(r, "_t0", None)
            if t0 is not None:
                self._ttfts.append((now - t0) * 1000.0)
            self._emit_token(b, int(toks0[i]))
        return True

    def _chunk_shape(self, slot: int, cap: int = 0) -> tuple[int, int, int, int]:
        """(start, n, bucket, skey) for a reserved slot's next chunk, with
        `cap` (>0) bounding n to the scheduler's remaining budget — same
        shape rules as GenerationEngine._chunk_shape (one executable per
        (group size, bucket, skey) forever)."""
        st = self._prefills[slot]
        start = st.done
        n = min(self.prefill_chunk, len(st.ids) - start)
        if cap > 0:
            n = min(n, cap)
        bucket = min(pow2_bucket(n, self.prefill_chunk), self.max_seq_len - start)
        skey = (
            min(pow2_bucket(start, self.max_seq_len), self.max_seq_len)
            if start
            else min(128, self.max_seq_len)
        )
        return start, n, bucket, skey

    def _try_prefill(self, reserved_tokens: int = 0) -> bool:
        """One budget-bounded chunk group per loop iteration: ask the shared
        TokenBudgetScheduler for this round's prefill token budget, stage a
        group of reserved slots' next chunks under it, broadcast the "chunk"
        command, and dispatch. Finished prompts activate (first token
        sampled from the replicated boundary logits, leader-locally).
        `reserved_tokens` is chunk work this iteration already owes to a
        staged speculative verify round."""
        n_active = sum(1 for s in self._slots if s is not None)
        if not self._prefill_q:
            self._sched.decide(0, n_active, 0.0)
            return False
        backlog = sum(len(st.ids) - st.done for st in self._prefills.values())
        oldest = min(self._prefills[s].t0 for s in self._prefill_q)
        budget = self._sched.decide(
            backlog, n_active, time.time() - oldest,
            reserved_tokens=reserved_tokens,
        )
        if budget <= 0:
            return False
        first = self._prefill_q[0]
        _, f_n, f_bucket, f_skey = self._chunk_shape(first, cap=budget)
        group = [first]
        used = f_n
        for slot in list(self._prefill_q)[1:]:
            if len(group) >= 4 or used >= budget:
                break
            start2, n2, _, s2 = self._chunk_shape(
                slot, cap=min(budget - used, f_bucket)
            )
            if s2 == f_skey and n2 > 0 and start2 + f_bucket <= self.max_seq_len:
                group.append(slot)
                used += n2
        Ab = 1 << (len(group) - 1).bit_length()
        tokens = np.zeros((Ab, f_bucket), np.int32)
        slots_arr = np.zeros((Ab,), np.int32)
        starts_arr = np.zeros((Ab,), np.int32)
        nv_arr = np.ones((Ab,), np.int32)
        metas: list[tuple[int, _SlicePrefill, int]] = []
        rem = budget
        for i, slot in enumerate(group):
            st = self._prefills[slot]
            start, n, _, _ = self._chunk_shape(
                slot, cap=min(rem, f_bucket) if i else budget
            )
            tokens[i, :n] = st.ids[start : start + n]
            slots_arr[i] = slot
            starts_arr[i] = start
            nv_arr[i] = n
            metas.append((slot, st, n))
            rem -= n
        for i in range(len(group), Ab):  # pad rows dup row 0: identical writes
            tokens[i] = tokens[0]
            slots_arr[i] = slots_arr[0]
            starts_arr[i] = starts_arr[0]
            nv_arr[i] = nv_arr[0]
        cmd = ("chunk", tokens, slots_arr, starts_arr, nv_arr,
               np.int32(f_skey))
        first = self._note_shape("chunk", Ab, f_bucket, f_skey)
        try:
            if self._leader_ch is not None:
                self._leader_ch.send(cmd)
            t0 = time.perf_counter()
            with self.mesh:
                logits, self._ck, self._cv = self._chunk_fn(
                    self.params, self._ck, self._cv, tokens,
                    slots_arr, starts_arr, nv_arr, int(f_skey),
                )
            jax.block_until_ready(self._ck)
            wall = time.perf_counter() - t0
            if first:
                self._compile_obs("chunk", (Ab, f_bucket, f_skey), wall)
            self._flight.event(
                "chunk", rows=len(group),
                tokens=sum(n for _, _, n in metas), bucket=f_bucket,
                wall_ms=round(wall * 1e3, 1),
            )
            self._sched.observe_prefill(
                sum(n for _, _, n in metas), wall,
                padded_tokens=Ab * f_bucket,
            )
        except Exception as e:
            # fail the group's waiters HERE (the loop's crash handler drains
            # the rest): the donated cache died with the dispatch
            for slot, st, _ in metas:
                self._prefills.pop(slot, None)
                try:
                    self._prefill_q.remove(slot)
                except ValueError:
                    pass
                self._paging.free_slot(slot)
                st.req.out.put({"type": "error", "error": repr(e)})
                st.req.out.put(_DONE)
            raise
        now = time.time()
        for i, (slot, st, n) in enumerate(metas):
            st.done += n
            if st.done < len(st.ids):
                continue
            # last chunk landed: activate. The logits are replicated, so the
            # leader samples locally — followers never need the token (every
            # decode command ships the full token block from the leader).
            r = st.req
            key = jax.random.fold_in(self._base_key, self._counter)
            self._counter += 1
            tok0 = int(np.asarray(sample_tokens(
                jnp.asarray(np.asarray(logits)[i : i + 1]), key,
                np.asarray([r.temperature], np.float32),
                np.asarray([r.top_k], np.int32),
                np.asarray([r.top_p], np.float32),
            ))[0])
            self._prefill_q.remove(slot)
            del self._prefills[slot]
            self._blk_ops += self._paging.ensure_slot(slot, len(st.ids))
            want = min(
                len(st.ids) + max(0, r.max_tokens) + self.decode_chunk,
                self.max_seq_len,
            )
            self._paging.note_admit_cost(self._paging.blocks_for(want))
            new_slot = _Slot(req=r, prompt_len=len(st.ids), active_at=now)
            if self.spec_enabled:
                new_slot.spec = NGramDrafter(
                    self.spec_min_ngram, self.spec_max_ngram
                )
                new_slot.spec.extend(st.ids)
            self._slots[slot] = new_slot
            self._toks[slot] = tok0
            self._lens[slot] = len(st.ids)  # un-park
            self._temps[slot] = r.temperature
            self._topks[slot] = r.top_k
            self._topps[slot] = r.top_p
            self._ttfts.append((now - st.t0) * 1000.0)
            self._emit_token(slot, tok0)
        return True

    def _stage_spec(self) -> list[tuple[int, list[int]]] | None:
        """Propose drafts for a speculative verify round (engine.py policy,
        slice flavor), or None to run a normal decode round. Every active
        slot joins (zero-draft rows degenerate to one-token decode steps);
        the round runs only when a MAJORITY of slots have drafts and every
        row has C = K+1 positions of cache headroom (dynamic_update_slice
        CLAMPS out-of-range starts — a clamped verify write would overwrite
        live KV)."""
        if not self.spec_enabled:
            return None
        if self._spec_cooldown > 0:
            self._spec_cooldown -= 1
            return None
        C = self.spec_k + 1
        entries: list[tuple[int, list[int]]] = []
        n_drafting = 0
        for b, s in enumerate(self._slots):
            if s is None:
                continue
            if s.spec is None:
                return None
            if int(self._lens[b]) + C > self.max_seq_len - 1:
                return None
            d = s.spec.draft(self.spec_k)
            if d:
                n_drafting += 1
            entries.append((b, d))
        if not entries or n_drafting == 0 or 2 * n_drafting < len(entries):
            return None
        return entries

    def _try_verify(self, entries: list[tuple[int, list[int]]]) -> bool:
        """One speculative verify round in place of the decode round:
        broadcast the budgeted "verify" command, dispatch the chunk pass over
        [token, draft_1..draft_nd] per slot, accept the longest agreeing
        prefix, and roll lengths forward to the accepted position (rows past
        it are dead by the parked-slot OOB invariant — rollback is pure
        arithmetic)."""
        B = self.max_slots
        Kd = self.spec_k
        C = Kd + 1
        n = len(entries)
        A = 1 << (n - 1).bit_length()
        tokens = np.zeros((A, C), np.int32)
        slots_arr = np.full((A,), B, np.int32)  # pads OOB: writes drop
        starts_arr = np.zeros((A,), np.int32)
        nv_arr = np.ones((A,), np.int32)
        drafts_arr = np.zeros((A, Kd), np.int32)
        nd_arr = np.zeros((A,), np.int32)
        temps = np.ones((A,), np.float32)
        topks = np.zeros((A,), np.int32)
        topps = np.ones((A,), np.float32)
        total = 0
        for i, (b, d) in enumerate(entries):
            nd = len(d)
            tokens[i, 0] = self._toks[b]
            if nd:
                tokens[i, 1 : 1 + nd] = d
                drafts_arr[i, :nd] = d
            slots_arr[i] = b
            starts_arr[i] = self._lens[b]
            nv_arr[i] = 1 + nd
            nd_arr[i] = nd
            temps[i] = self._temps[b]
            topks[i] = self._topks[b]
            topps[i] = self._topps[b]
            total += 1 + nd
        skey = min(
            pow2_bucket(int(starts_arr[:n].max()), self.max_seq_len),
            self.max_seq_len,
        )
        ctr = self._counter
        self._counter += 1
        cmd = ("verify", tokens, slots_arr, starts_arr, nv_arr, drafts_arr,
               nd_arr, temps, topks, topps, np.int32(ctr), np.int32(skey))
        first = self._note_shape("verify", A, C, skey)
        t0 = time.perf_counter()
        if self._leader_ch is not None:
            self._leader_ch.send(cmd)
        with self.mesh:
            n_acc, final, self._ck, self._cv = self._verify_fn(
                self.params, self._ck, self._cv, tokens, slots_arr,
                starts_arr, nv_arr, drafts_arr, nd_arr, temps, topks, topps,
                np.int32(ctr), int(skey),
            )
        n_acc = np.asarray(n_acc)  # replicated: local fetch
        final = np.asarray(final)
        if first:
            self._compile_obs("verify", (A, C, skey), time.perf_counter() - t0)
        self._sched.observe_verify(total, time.perf_counter() - t0)
        K = self.decode_chunk
        drafted_round = accepted_round = emitted_round = 0
        blk_wants: dict[int, int] = {}
        for i, (b, d) in enumerate(entries):
            s = self._slots[b]
            if s is None:
                continue
            na = min(int(n_acc[i]), len(d))
            base_b = int(starts_arr[i])
            drafted_round += len(d)
            accepted_round += na
            for tok in list(d[:na]) + [int(final[i])]:
                emitted_round += 1
                self._emit_token(b, int(tok))
                if self._slots[b] is not s:
                    break  # finished mid-round (eos / stop / max_tokens)
            if self._slots[b] is s:
                # commit: KV valid through base+na; `final`'s KV is written
                # by the next round at the rolled-forward length
                self._lens[b] = base_b + 1 + na
                self._toks[b] = np.int32(final[i])
                blk_wants[b] = base_b + 1 + na
                if int(self._lens[b]) + K > self.max_seq_len - 1:
                    self._finish_slot(b, "length")
        if blk_wants:
            self._blk_ops += self._paging.extend_many(blk_wants)
        self._tps_marks.append((time.time(), emitted_round))
        self.spec_calls += 1
        self.spec_drafted += drafted_round
        self.spec_accepted += accepted_round
        self.spec_emitted += emitted_round
        self._flight.event(
            "verify", rows=n, drafted=drafted_round, accepted=accepted_round,
        )
        if drafted_round and accepted_round * 4 < drafted_round:
            # drafts aren't landing: a verify round emits >=1 token per slot
            # where a decode round emits K — back off before re-probing
            self._spec_cooldown = 50
        return True

    def _try_decode(self) -> bool:
        active0 = np.asarray([s is not None for s in self._slots], bool)
        if not active0.any():
            return False
        t_round = time.perf_counter()
        ctr = self._counter
        self._counter += 1
        cmd = ("decode", self._toks.copy(), self._lens.copy(), active0.copy(),
               self._temps.copy(), self._topks.copy(), self._topps.copy(),
               np.int32(ctr))
        first = self._note_shape("decode", self.max_slots, self.decode_chunk)
        if self._leader_ch is not None:
            self._leader_ch.send(cmd)
        with self.mesh:
            out, self._ck, self._cv = self._decode_fn(
                self.params, self._ck, self._cv, self._toks, self._lens,
                active0, self._temps, self._topks, self._topps, np.int32(ctr),
            )
        out = np.asarray(out)  # [K, B] replicated
        if first:
            self._compile_obs(
                "decode", (self.max_slots, self.decode_chunk),
                time.perf_counter() - t_round,
            )
        # decode rounds here are never fused with prefill, so every round
        # teaches the scheduler's decode-round EMA directly
        self._sched.observe_decode(time.perf_counter() - t_round)
        K = out.shape[0]
        self._flight.event("decode", rows=int(active0.sum()))
        self._tps_marks.append((time.time(), int(active0.sum()) * K))
        for k in range(K):
            for b in range(self.max_slots):
                if not active0[b] or self._slots[b] is None:
                    continue  # finished mid-round: ignore its later tokens
                self._emit_token(b, int(out[k, b]))
        live = np.asarray([s is not None for s in self._slots], bool)
        self._toks = np.where(live, out[-1], self._toks).astype(np.int32)
        # the device advanced lengths once per step for every row active at
        # round START (its `active` is constant through the scan)
        adv = np.where(active0, K, 0).astype(np.int32)
        self._lens = self._lens + adv
        self._blk_ops += self._paging.extend_many({
            b: int(self._lens[b])
            for b in range(self.max_slots)
            if active0[b] and self._slots[b] is not None
        })
        # a round writes K/V at positions lens..lens+K-1: a slot without a
        # full round of headroom must finish NOW — an out-of-bounds cache
        # write would be clamped/dropped and the tokens sampled from that
        # corrupted attention state would stream to the client
        for b in range(self.max_slots):
            if self._slots[b] is not None and (
                int(self._lens[b]) + K > self.max_seq_len - 1
            ):
                self._finish_slot(b, "length")
        return True

    def _emit_token(self, b: int, tok: int) -> None:
        slot = self._slots[b]
        if slot is None:
            return
        req = slot.req
        self.total_tokens += 1
        slot.generated += 1
        eos = getattr(self.tokenizer, "eos_id", -1)
        finish = None
        if eos is not None and tok == eos:
            finish = "stop"
            text = ""
        else:
            text, slot.pending = self.tokenizer.decode_stream(slot.pending, [tok])
            if slot.spec is not None:
                slot.spec.append(tok)  # drafter history = committed tokens
        if text:
            slot.text += text
            for stop_s in req.stop:
                idx = slot.text.find(stop_s)
                if idx >= 0:
                    # emit up to the stop string, then finish
                    keep = idx - (len(slot.text) - len(text))
                    if keep > 0:
                        req.out.put({"type": "token", "text": text[:keep]})
                    finish = "stop"
                    text = ""
                    break
            if text and finish is None:
                req.out.put({"type": "token", "text": text})
                if self._pool is not None:
                    slot.last_emit = time.time()
        if finish is None and slot.generated >= req.max_tokens:
            finish = "length"
        if finish is not None:
            self._finish_slot(b, finish)

    def _finish_slot(self, b: int, finish: str) -> None:
        slot = self._slots[b]
        if slot is None:
            return
        req = slot.req
        tail = self.tokenizer.decode_flush(slot.pending)
        if tail and finish != "stop":
            req.out.put({"type": "token", "text": tail})
        req.out.put({
            "type": "done",
            "finish_reason": finish,
            "usage": {
                "prompt_tokens": slot.prompt_len,
                "completion_tokens": slot.generated,
                "total_tokens": slot.prompt_len + slot.generated,
            },
        })
        req.out.put(_DONE)
        self._slots[b] = None
        self._blk_ops += self._paging.free_slot(b)
