"""Multi-host serving engine — compatibility facade over the unified plane.

Historically this module held a second, hand-mirrored scheduling loop: every
engine feature (chunked prefill, speculation, preemption, paging, the prefix
tier) existed twice, once in `GenerationEngine` and once here as a
per-feature command (`chunk`/`verify`/`preempt`/`restore`/`blk`/…) the
leader broadcast and followers pattern-matched. That fork is gone.

`GenerationEngine` (executor/engine.py) is now the ONLY scheduling loop; the
multi-host behavior lives entirely in the `DispatchBackend` seam
(executor/dispatch.py): every device mutation the loop makes flows through
one funnel (`_dx`) that serializes an (op, host-payload) step-program to
follower processes, which replay it through the same op registry. No
scheduling state crosses the wire and no per-feature mirror code exists —
the dispatch-surface lint pass (analysis/dispatch_surface.py) enforces that
it never comes back.

`SliceEngine` survives as a thin constructor shim for existing callers and
boot scripts: it is `GenerationEngine` wired to a `GSPMDBackend`, keeping
the old keyword surface (`cmd_addr`, `connect_timeout_s`, the strict
quant-with-checkpoint error, the `max_slots % dp` check). Construct it in
EVERY process of the cluster with identical arguments; `.start()` on the
leader (process 0), `.run_follower()` everywhere else — both inherited.

The command channel primitives (`CmdLeader`, `CmdFollower`,
`PING_INTERVAL_S`) moved to executor/dispatch.py and are re-exported here
for import compatibility.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from .dispatch import (  # noqa: F401  (compat re-exports)
    PING_INTERVAL_S,
    CmdFollower,
    CmdLeader,
    GSPMDBackend,
)
from .engine import GenerationEngine, GenRequest
from .tokenizer import Tokenizer

__all__ = [
    "SliceEngine",
    "SliceRequest",
    "CmdLeader",
    "CmdFollower",
    "PING_INTERVAL_S",
]

# The slice request type was always structurally identical to the engine's;
# now it IS the engine's (one loop, one queue, one request dataclass).
SliceRequest = GenRequest


class SliceEngine(GenerationEngine):
    """`GenerationEngine` over a `GSPMDBackend` — the multi-host spelling of
    the one unified engine. See module docstring."""

    def __init__(
        self,
        model: str | ModelConfig = "tiny-llm",
        *,
        mesh: Any,
        cmd_addr: str,
        max_slots: int = 8,
        max_seq_len: int = 256,
        dtype: Any = jnp.bfloat16,
        decode_chunk: int = 8,
        quant: str = "",
        weights_dir: str = "",
        tokenizer: Tokenizer | None = None,
        seed: int = 0,
        connect_timeout_s: float = 60.0,
        prefill_chunk: int = 0,
        target_ttft_ms: float = 2000.0,
        **engine_kw: Any,
    ):
        if quant not in ("", "int8") and weights_dir:
            # The unified engine downgrades unknown quant modes to a warning;
            # a multi-host boot must not silently serve different bytes than
            # the operator asked for across a whole slice.
            raise NotImplementedError(
                f"slice engine quant={quant!r} with a checkpoint "
                f"(only 'int8' is supported)"
            )
        if mesh is not None:
            dp = dict(mesh.shape).get("dp", 1)
            if max_slots % max(dp, 1) != 0:
                raise ValueError(
                    f"max_slots {max_slots} must divide over dp={dp}"
                )
        super().__init__(
            model,
            mesh=mesh,
            backend=GSPMDBackend(cmd_addr, connect_timeout_s=connect_timeout_s),
            max_slots=max_slots,
            max_seq_len=max_seq_len,
            dtype=dtype,
            decode_chunk=decode_chunk,
            quant=quant,
            weights_dir=weights_dir,
            tokenizer=tokenizer,
            seed=seed,
            prefill_chunk=prefill_chunk,
            target_ttft_ms=target_ttft_ms,
            **engine_kw,
        )
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.is_leader = self.process_index == 0
