"""Minimal multi-process SliceEngine demo entrypoint.

Run one copy per process of a `jax.distributed` cluster (the standard env
triplet JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, plus
SLICE_CMD_ADDR for the leader→follower command channel):

    python -m llm_mcp_tpu.executor.slice_demo

The leader (process 0) generates a short greedy completion through the
sliced engine — every decode round's dp axis crosses the process boundary —
and prints `SLICE DEMO OK`; followers mirror the dispatches and exit on the
leader's stop command. Used by `__graft_entry__.dryrun_multichip` to prove
the multi-host serving engine executes, and serves as the template for a
real multi-host deployment (swap tiny-llm for the production model and wrap
the leader in CoreServer — tests/test_slice_engine.py does exactly that)."""

from __future__ import annotations

import os


def main() -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        n = os.environ.get("SLICE_LOCAL_DEVICES", "4")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip()
        )
    import jax

    if os.environ.get("SLICE_DEMO_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ..parallel import distributed
    from .slice_engine import SliceEngine

    if not distributed.initialize():
        raise SystemExit("slice demo needs a jax.distributed env triplet")
    mesh_spec = os.environ.get("SLICE_MESH", "dp=4,tp=2")
    mesh = distributed.make_global_mesh(mesh_spec)
    eng = SliceEngine(
        os.environ.get("SLICE_MODEL", "tiny-llm"),
        mesh=mesh,
        cmd_addr=os.environ["SLICE_CMD_ADDR"],
        max_slots=int(os.environ.get("SLICE_SLOTS", "8")),
        max_seq_len=int(os.environ.get("SLICE_SEQ", "128")),
        dtype=jnp.float32,
        decode_chunk=4,
    )
    if jax.process_index() == 0:
        eng.start()
        out = eng.generate("slice dryrun", max_tokens=6, temperature=0.0)
        assert out["usage"]["completion_tokens"] >= 1, out
        eng.shutdown()
        print(
            f"SLICE DEMO OK: {jax.process_count()} processes, "
            f"mesh {mesh_spec}, {out['usage']['completion_tokens']} tokens",
            flush=True,
        )
    else:
        eng.run_follower()
        print("SLICE FOLLOWER OK", flush=True)


if __name__ == "__main__":
    main()
