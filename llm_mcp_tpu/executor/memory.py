"""HBM-aware KV pool: admission accounting, preemption policy, host offload.

The engines own a static `[layers, max_slots, heads, max_seq_len, head_dim]`
KV cache (plus int8-dict and MLA-latent variants) sized at construction; a
slot is pinned for a request's whole life and an overloaded engine simply
starves its admission queue. This module adds the memory-manager layer in the
style of vLLM's PagedAttention pool (Kwon et al., 2023) and Sarathi-Serve's
SLO-aware admission, without repaginating the cache:

  - **Accounting**: bytes per slot are measured from the live cache pytree
    (`pytree_nbytes`), so kv8's `{q: int8, s: scale}` dict and MLA's
    asymmetric latent k/v layouts are covered without layout-specific code.
  - **Admission**: `admit_ok(offered)` compares offered load (active slots +
    queued + preempted) against `watermark × max_slots`. Above the
    watermark the API sheds (429 + Retry-After) instead of queueing work
    that cannot run.
  - **Preemption**: `pick_victim` orders candidates by policy — "priority"
    (lowest priority, then longest-idle, then most-tokens-remaining),
    "idle" (longest-idle first), "tokens" (most-remaining first),
    "slo_debt" (largest per-tenant goodput surplus first — the tenant
    whose SLO ratio is furthest ABOVE its peers has the most slack to
    give back). Every policy first prefers candidates with a larger
    `slo_surplus` (the engine stamps it from the perf observatory's
    per-tenant goodput ratios); with tenancy off the key is absent,
    every surplus reads 0.0, and ordering is byte-identical to the
    pre-zoo policies. The
    engine snapshots the victim's committed KV rows to host memory
    (`jax.device_get` of a dynamic slice — exact by the committed-lengths
    invariant: rows past the committed length are dead and rewritten in
    place), frees the slot, and later restores via `device_put` + the
    `_insert_row` donation path. Greedy output is token-identical across a
    preempt/restore cycle (pinned by tests/test_memory_pool.py).

The pool itself is pure host-side bookkeeping — no jax imports, no device
calls — so the engines keep every device interaction in their own dispatch
paths and `TPU_KV_HOST_OFFLOAD=0` (pool never constructed) stays a true
no-op. All mutating entry points take an internal lock: the engine thread
mutates while API threads read `stats()`/`admission` concurrently.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from ..utils.locks import OrderedLock

__all__ = ["KVPool", "KVSnapshot", "pytree_nbytes", "bucket_len"]

POLICIES = ("priority", "idle", "tokens", "slo_debt")

# Thrash guards: at most one preemption per interval, and restores are
# aged past fairness after this many multiples of the scheduler's TTFT
# target (a low-priority snapshot cannot starve forever behind a stream
# of high-priority arrivals, and vice versa).
PREEMPT_MIN_INTERVAL_S = 1.0
RESTORE_AGING_TTFT_MULT = 2.0


def pytree_nbytes(tree: Any) -> int:
    """Total bytes of every array leaf in a nested dict/list/tuple pytree.

    Layout-agnostic HBM accounting: covers bf16 `[L,B,H,S,hd]`, the kv8
    `{"q": int8, "s": scale}` dict, and MLA's asymmetric latent k/v without
    enumerating layouts. Leaves only need `.size` and `.dtype.itemsize`
    (numpy and jax arrays both qualify)."""
    if isinstance(tree, dict):
        return sum(pytree_nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(pytree_nbytes(v) for v in tree)
    size = getattr(tree, "size", None)
    dtype = getattr(tree, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * int(dtype.itemsize)


def bucket_len(length: int, max_seq_len: int) -> int:
    """Power-of-two snapshot bucket >= length, capped at max_seq_len.

    Snapshot/restore traffic reuses the engines' pow2 executable buckets so
    a preempt/restore cycle compiles at most one slice shape per bucket
    instead of one per request length."""
    b = 1
    while b < length:
        b *= 2
    return max(1, min(b, max_seq_len))


@dataclass
class KVSnapshot:
    """A preempted slot's exact host-side state.

    `k_rows`/`v_rows` hold the committed KV rows `[0, bucket)` (host numpy,
    possibly a dict for kv8). Restore may write the whole bucket back: rows
    in `[length, bucket)` are dead by the committed-lengths invariant — the
    first post-restore decode round overwrites position `length` before any
    read attends to it."""

    req_id: str
    priority: int
    length: int
    bucket: int
    last_tok: int
    temperature: float
    top_k: int
    top_p: float
    k_rows: Any
    v_rows: Any
    nbytes: int
    preempted_at: float
    slot_obj: Any = None  # the engine's live slot record, reinstalled on restore
    # SliceEngine protocol: every process stores its own host copy of the
    # rows keyed by this id, so the "restore" command ships (slot, snap_id)
    # instead of the KV payload over the command channel. -1 = single-host.
    snap_id: int = -1
    # Paged KV (executor/paging.py): when the victim was admitted off a
    # shared prefix, `k_rows`/`v_rows` hold only the PRIVATE rows
    # `[shared_len, bucket)` — the shared rows stay pinned as block ids in
    # the paging ledger and are re-inserted on restore from `shared_entry`
    # (the prefix-cache entry's device arrays, kept alive by this
    # reference even across an eviction). 0 = whole-bucket snapshot.
    shared_len: int = 0
    shared_entry: Any = None
    # KV migration (executor/migration.py): the shared prefix's token key —
    # rides the wire so the DESTINATION engine can re-pin the prefix blocks
    # out of its own cache (`admit_shared`) instead of copying rows. None
    # for within-engine preemption, where shared_entry alone suffices.
    shared_key: Any = None
    # True for a snapshot that arrived over the transfer endpoint: restore
    # then records an engine.migrate_in span (not engine.restore), skips
    # the pool's restored counter, and pins shared blocks via admit_shared
    # rather than re-tabling parked pins it never had.
    migrated: bool = False
    # Physical paged KV (executor/physical.py): the prefix-pool row indices
    # backing the shared blocks, captured from the victim's live block table
    # at snapshot time. A PHYSICAL prefix entry keeps no device row copies,
    # so the migration wire's fallback rows gather from these pool rows —
    # which stay valid while the parked pins (or the exporting slot's table)
    # keep the ledger ids alive. None for contiguous entries.
    shared_pool_rows: Any = None


class KVPool:
    def __init__(
        self,
        *,
        max_slots: int,
        max_seq_len: int,
        bytes_per_slot: int,
        watermark: float = 1.5,
        policy: str = "priority",
        max_preempted: int | None = None,
    ):
        if policy not in POLICIES:
            raise ValueError(f"unknown preempt policy {policy!r}; expected one of {POLICIES}")
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len)
        self.bytes_per_slot = int(bytes_per_slot)
        self.watermark = max(1.0, float(watermark))
        self.policy = policy
        # bound host memory: never hold more offloaded snapshots than slots
        self.max_preempted = int(max_preempted) if max_preempted else self.max_slots
        self._lock = OrderedLock("kvpool", rank=20)
        self._snaps: list[KVSnapshot] = []
        self._last_preempt_at = 0.0
        # cumulative counters (engines_info bridges deltas into Prometheus)
        self.preempted_total = 0
        self.restored_total = 0
        self.shed_total = 0
        self.offload_bytes_total = 0
        self.offload_seconds_total = 0.0
        self.restore_seconds_total = 0.0

    # -- accounting --------------------------------------------------------

    def hbm_bytes(self) -> int:
        return self.max_slots * self.bytes_per_slot

    def admit_ok(self, offered: float) -> bool:
        """True while offered load is under the oversubscription watermark.
        `offered` is in slot-equivalents: historically the integer count
        active + queued + preempted; with the paged-KV ledger it is the
        unique-block offered load / blocks_per_slot (executor/paging.py
        `offered_blocks`), which reduces to the same integer when nothing
        is shared. Side-effect free — callers that act on a shed decision
        record it via `note_shed()`."""
        return offered < self.watermark * self.max_slots

    def headroom(self, offered: float) -> float:
        """Fraction of shed-free capacity remaining, in [0, 1]. Advertised
        through device tags so the router de-ranks saturated devices."""
        cap = self.watermark * self.max_slots
        if cap <= 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - offered / cap))

    # -- preemption policy -------------------------------------------------

    def may_preempt(self, now: float | None = None) -> bool:
        """Rate limit + host-memory bound; side-effect free."""
        now = time.time() if now is None else now
        with self._lock:
            if len(self._snaps) >= self.max_preempted:
                return False
            return now - self._last_preempt_at >= PREEMPT_MIN_INTERVAL_S

    def pick_victim(self, candidates: list[dict]) -> dict | None:
        """Choose the slot to evict. Each candidate dict carries `priority`
        (int), `last_activity` (monotonic-ish seconds), `tokens_remaining`
        (int), optionally `slo_surplus` (float: the owning tenant's
        goodput_ratio surplus over the worst-served tenant), plus any
        engine-side handle keys (`slot`, ...). Returns the chosen
        candidate unmodified, or None when empty.

        SLO debt leads every policy: the slot whose tenant is furthest
        AHEAD of its SLO is preempted first — it has slack to give back,
        while preempting an already-behind tenant digs its debt deeper.
        Candidates without the key (single-tenant serving) all read 0.0,
        so ordering degrades exactly to the historical per-policy keys."""
        if not candidates:
            return None
        if self.policy == "idle":
            base = lambda c: (c["last_activity"], c["priority"], -c["tokens_remaining"])
        elif self.policy == "tokens":
            base = lambda c: (-c["tokens_remaining"], c["priority"], c["last_activity"])
        else:  # "priority"/"slo_debt": lowest priority, longest-idle, most-remaining
            base = lambda c: (c["priority"], c["last_activity"], -c["tokens_remaining"])
        key = lambda c: (-float(c.get("slo_surplus", 0.0)), *base(c))
        return min(candidates, key=key)

    # -- offload / restore bookkeeping --------------------------------------

    def offload(self, snap: KVSnapshot, seconds: float = 0.0) -> None:
        with self._lock:
            self._snaps.append(snap)
            self._last_preempt_at = max(self._last_preempt_at, snap.preempted_at)
            self.preempted_total += 1
            self.offload_bytes_total += int(snap.nbytes)
            self.offload_seconds_total += max(0.0, float(seconds))

    def preempted_count(self) -> int:
        with self._lock:
            return len(self._snaps)

    def has_preempted(self) -> bool:
        return self.preempted_count() > 0

    def peek_restore(self) -> KVSnapshot | None:
        """The snapshot next in line for restore (highest priority, then
        longest-preempted), without removing it."""
        with self._lock:
            if not self._snaps:
                return None
            return min(self._snaps, key=lambda s: (-s.priority, s.preempted_at))

    def pop_restore(self) -> KVSnapshot | None:
        with self._lock:
            if not self._snaps:
                return None
            snap = min(self._snaps, key=lambda s: (-s.priority, s.preempted_at))
            self._snaps.remove(snap)
            return snap

    def requeue(self, snap: KVSnapshot) -> None:
        """Put back a popped snapshot untouched (restore deferred by the
        fairness rule or by a missing free slot) — no counter moves."""
        with self._lock:
            self._snaps.append(snap)

    def discard(self, snap: KVSnapshot) -> None:
        """Drop a snapshot without restoring (owner aborted/finished)."""
        with self._lock:
            try:
                self._snaps.remove(snap)
            except ValueError:
                pass

    def note_restored(self, snap: KVSnapshot, seconds: float = 0.0) -> None:
        with self._lock:
            self.restored_total += 1
            self.restore_seconds_total += max(0.0, float(seconds))

    def note_shed(self, n: int = 1) -> None:
        with self._lock:
            self.shed_total += int(n)

    def drain(self) -> list[KVSnapshot]:
        """Remove and return every held snapshot (abort/shutdown paths: the
        engine errors each snapshot's waiter)."""
        with self._lock:
            snaps, self._snaps = self._snaps, []
            return snaps

    # -- telemetry -----------------------------------------------------------

    def stats(self) -> dict[str, float]:
        with self._lock:
            held = len(self._snaps)
            held_bytes = sum(int(s.nbytes) for s in self._snaps)
            return {
                "policy_" + self.policy: 1.0,  # which policy is live, greppable
                "watermark": float(self.watermark),
                "hbm_bytes": float(self.hbm_bytes()),
                "bytes_per_slot": float(self.bytes_per_slot),
                "preempted_held": float(held),
                "preempted_held_bytes": float(held_bytes),
                "preempted_total": float(self.preempted_total),
                "restored_total": float(self.restored_total),
                "shed_total": float(self.shed_total),
                "offload_bytes_total": float(self.offload_bytes_total),
                "offload_seconds_total": self.offload_seconds_total,
                "restore_seconds_total": self.restore_seconds_total,
            }
