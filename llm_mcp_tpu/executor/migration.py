"""KV migration: wire format + coordinator for engine-to-engine rebalancing.

PR 4's preempt path already produces the migration primitive — a
token-identical host snapshot of a slot's committed KV rows (memory.py
`KVSnapshot`) that restores through the donated insert path. This module
moves that snapshot *between* engines instead of round-tripping it within
one, in the style of DistServe (OSDI'24) / Splitwise (ISCA'24):

  - **Wire format**: `encode_payload`/`decode_payload` serialize a snapshot
    plus the request's continuation state (sampling params, generated text,
    tokenizer byte-carry) into `magic | version | header-json | raw blobs`.
    The tree codec covers every cache layout without enumerating them —
    bf16 GQA's bare array, kv8's `{"q","s"}` dict, the fused int8 payload's
    `v == {}` sentinel, and MLA's asymmetric latents are all just
    {ndarray | dict} trees. Paged private-only snapshots ride as-is: the
    shared prefix travels as a token key (re-pinned on the destination via
    `admit_shared` when its prefix cache holds the same entry) with the
    shared rows attached as a fallback for destinations that never saw the
    prefix.
  - **MigrationCoordinator**: the orchestration plane. Pumps prefill-role
    engines' outboxes to decode-capable targets (disaggregated mode,
    `TPU_ROLE=prefill|decode|both`) and drains a saturated engine — one
    whose `kv_headroom` fell under `drain_low` while a peer sits above
    `drain_high` — by moving offloaded snapshots, then plain queued
    requests, to the idle peer. Targets are duck-typed: a local engine
    (`migrate_import`) or an rpc proxy that ships the payload over the
    transfer endpoint and pumps the returned event stream.

This file is intentionally dependency-free (stdlib + numpy on the wire
path, no jax/grpc imports — pinned by tests/test_migration.py's
import-lint) so a CPU-only worker can decode and forward payloads without
an accelerator stack installed. Every device interaction stays in
engine.py's export/import hooks.

Locking: the coordinator's lock ranks BELOW every engine lock
(migration=5 < engine.stats=10 < kvpool=20 < paging=30, doc/concurrency.md)
because a tick holds it while calling into engine export/import paths that
take stats/pool/paging locks. No engine thread ever takes the migration
lock, so the reverse order cannot occur.
"""

from __future__ import annotations

import base64
import json
import logging
import struct
import threading
import time
from typing import Any, Callable

import numpy as np

from ..utils.locks import OrderedLock
from .memory import KVSnapshot, pytree_nbytes

log = logging.getLogger("executor.migration")

__all__ = [
    "MIGRATION_LOCK_RANK",
    "MigrationCoordinator",
    "decode_payload",
    "encode_payload",
    "merge_shared_rows",
    "wire_to_snapshot",
]

# doc/concurrency.md: below every engine-side lock — a coordinator tick
# holds this while calling export/import hooks that take ranks 10/20/30.
MIGRATION_LOCK_RANK = 5

_MAGIC = b"KVMG"
_VERSION = 1
_HDR = struct.Struct("<4sBBI")  # magic, version, flags, header_len

ROLES = ("prefill", "decode", "both")


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, reaching for ml_dtypes' extended registry
    (bfloat16, ...) only when plain numpy does not know it. ml_dtypes is a
    numpy extension independent of jax, and only payloads that actually
    carry such arrays need it — a CPU-only forwarder never resolves
    dtypes at all."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # deferred: never needed on the forward-only path

        return np.dtype(getattr(ml_dtypes, name))


def _encode_tree(tree: Any, blobs: list[bytes]) -> Any:
    """Depth-first walk appending each leaf's raw bytes to `blobs` and
    returning a JSON-able meta mirror of the structure. Decode replays the
    identical walk, so blob order is implied by the meta alone."""
    if tree is None:
        return None
    if isinstance(tree, dict):
        # {} is a live layout sentinel (fused int8 GQA's cv), not absence
        return {"m": {k: _encode_tree(v, blobs) for k, v in tree.items()}}
    arr = np.asarray(tree)
    blobs.append(arr.tobytes())
    return {"d": str(arr.dtype), "s": list(arr.shape)}


def _decode_tree(meta: Any, buf: memoryview, off: int) -> tuple[Any, int]:
    if meta is None:
        return None, off
    if "m" in meta:
        out = {}
        for k, sub in meta["m"].items():
            out[k], off = _decode_tree(sub, buf, off)
        return out, off
    dt = _np_dtype(meta["d"])
    shape = tuple(meta["s"])
    n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize if shape else dt.itemsize
    arr = np.frombuffer(buf, dtype=dt, count=max(1, n // dt.itemsize), offset=off)
    return arr.reshape(shape).copy(), off + n


def encode_payload(header: dict[str, Any], trees: dict[str, Any]) -> bytes:
    """`header` is arbitrary JSON-able continuation state; `trees` maps
    names to {ndarray | dict | None} pytrees shipped as raw blobs."""
    blobs: list[bytes] = []
    meta = {name: _encode_tree(t, blobs) for name, t in trees.items()}
    hdr = json.dumps({"h": header, "t": meta}, separators=(",", ":")).encode()
    return b"".join([_HDR.pack(_MAGIC, _VERSION, 0, len(hdr)), hdr, *blobs])


def decode_payload(data: bytes) -> tuple[dict[str, Any], dict[str, Any]]:
    if len(data) < _HDR.size:
        raise ValueError("migration payload truncated")
    magic, version, _flags, hlen = _HDR.unpack_from(data)
    if magic != _MAGIC:
        raise ValueError("not a migration payload (bad magic)")
    if version != _VERSION:
        raise ValueError(f"migration payload version {version} != {_VERSION}")
    hdr = json.loads(bytes(data[_HDR.size : _HDR.size + hlen]))
    buf = memoryview(data)
    off = _HDR.size + hlen
    trees: dict[str, Any] = {}
    for name, meta in hdr["t"].items():
        trees[name], off = _decode_tree(meta, buf, off)
    return hdr["h"], trees


def merge_shared_rows(shared: Any, private: Any) -> Any:
    """Concatenate shared-prefix rows ahead of private rows along the seq
    axis (ALWAYS axis 3 across every layout) — the fallback when the
    destination's prefix cache cannot re-pin the shared blocks."""
    if isinstance(shared, dict):
        if not shared:
            return {}
        return {k: merge_shared_rows(shared[k], private[k]) for k in shared}
    return np.concatenate([np.asarray(shared), np.asarray(private)], axis=3)


def snapshot_header(snap: KVSnapshot, req: Any, slot: Any) -> dict[str, Any]:
    """Continuation state for `snap`'s request: everything the destination
    needs to resume emission mid-stream — sampling params for the device
    rows, generated text for stop-sequence scanning, the tokenizer's
    undecoded byte carry, and the prompt ids (prefix-cache key matching +
    usage accounting)."""
    return {
        "request_id": snap.req_id,
        "priority": snap.priority,
        "length": snap.length,
        "bucket": snap.bucket,
        "last_tok": snap.last_tok,
        "temperature": snap.temperature,
        "top_k": snap.top_k,
        "top_p": snap.top_p,
        "shared_len": snap.shared_len,
        "shared_key": list(snap.shared_key) if snap.shared_key else None,
        "max_tokens": int(req.max_tokens),
        "stop": list(req.stop),
        "prompt_ids": [int(t) for t in req.prompt_ids],
        "created_at": float(req.created_at),
        "trace_ctx": req.trace_ctx,
        "migrations": int(getattr(req, "migrations", 0)),
        "generated": int(slot.generated),
        "text": slot.text,
        "pending_b64": base64.b64encode(slot.pending).decode("ascii"),
        "prompt_len": int(slot.prompt_len),
        # grammar-constrained decoding: ship the raw spec + the ids the
        # automaton has consumed; the destination recompiles against its
        # own cache and replays to the same state (automaton internals
        # never cross the wire — they are engine-local memo tables)
        "constraint": getattr(req, "constraint", None),
        "logit_bias": getattr(req, "logit_bias", None),
        "cn_tokens": (
            [int(t) for t in slot.cn.consumed]
            if getattr(slot, "cn", None) is not None
            else None
        ),
    }


def wire_to_snapshot(data: bytes) -> tuple[dict[str, Any], KVSnapshot]:
    """Decode a payload into (header, KVSnapshot). The snapshot arrives
    with `slot_obj=None` and `snap_id=-1` — the importing engine installs
    its own slot record and a destination-local snap id. When the payload
    carried fallback shared rows and the header names a shared prefix, the
    caller decides: re-pin via the destination prefix cache (keep
    `shared_len`, drop the fallback) or merge the fallback rows back into
    a whole-bucket snapshot."""
    header, trees = decode_payload(data)
    snap = KVSnapshot(
        req_id=header["request_id"],
        priority=int(header["priority"]),
        length=int(header["length"]),
        bucket=int(header["bucket"]),
        last_tok=int(header["last_tok"]),
        temperature=float(header["temperature"]),
        top_k=int(header["top_k"]),
        top_p=float(header["top_p"]),
        k_rows=trees["k"],
        v_rows=trees["v"],
        nbytes=pytree_nbytes(trees["k"]) + pytree_nbytes(trees["v"]),
        preempted_at=time.time(),
        shared_len=int(header.get("shared_len") or 0),
        shared_key=tuple(header["shared_key"]) if header.get("shared_key") else None,
        migrated=True,
    )
    if snap.shared_len and trees.get("shared_k") is not None:
        # stash the fallback rows on the snapshot so the importer can merge
        # without re-decoding the payload
        snap.shared_entry = {"k": trees["shared_k"], "v": trees["shared_v"]}
    return header, snap


def flatten_to_whole_bucket(snap: KVSnapshot) -> None:
    """Fold fallback shared rows into the private rows, turning a paged
    private-only snapshot into a plain whole-bucket one (destination has no
    matching prefix entry to re-pin)."""
    if not snap.shared_len:
        return
    if snap.shared_entry is None:
        raise ValueError(
            f"snapshot {snap.req_id[:8]} has a {snap.shared_len}-token shared "
            "prefix but no fallback rows and no matching destination entry"
        )
    snap.k_rows = merge_shared_rows(snap.shared_entry["k"], snap.k_rows)
    snap.v_rows = merge_shared_rows(snap.shared_entry["v"], snap.v_rows)
    snap.nbytes = pytree_nbytes(snap.k_rows) + pytree_nbytes(snap.v_rows)
    snap.shared_len = 0
    snap.shared_entry = None
    snap.shared_key = None


class MigrationCoordinator:
    """Moves work between engines: outbox pumping (disaggregated
    prefill→decode handoff) and headroom-driven drain of a saturated
    engine. Engines are duck-typed — anything with `migrate_import`
    qualifies as a target (rpc.client.RemoteMigrationTarget ships the
    payload over the transfer endpoint), while sources additionally need
    the engine-side export hooks (`_migrate_outbox`, `migrate_export_one`,
    `migrate_steal_queued`).

    `tick()` is the whole control loop — call it from a periodic thread
    (`start()`) or an existing ticker (api/server.py). All bookkeeping sits
    under the rank-5 migration lock; engine calls happen while holding it,
    which is legal because every engine lock ranks higher."""

    def __init__(
        self,
        engines: dict[str, Any],
        *,
        roles: dict[str, str] | None = None,
        role: str = "both",
        drain_low: float = 0.25,
        drain_high: float = 0.5,
        burst: int = 2,
        interval_s: float = 0.5,
    ):
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; expected one of {ROLES}")
        self.engines = dict(engines)
        self.roles = {n: (roles or {}).get(n, role) for n in self.engines}
        for n, r in self.roles.items():
            if r not in ROLES:
                raise ValueError(f"unknown role {r!r} for engine {n!r}")
        self.drain_low = float(drain_low)
        self.drain_high = float(drain_high)
        self.burst = max(1, int(burst))
        self.interval_s = float(interval_s)
        self._remote: dict[str, Any] = {}
        self._lock = OrderedLock("migration", rank=MIGRATION_LOCK_RANK)
        self._pressure = threading.Event()  # admission shed observed: drain now
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # cumulative counters (engines_info bridges deltas into Prometheus)
        self.snapshots_moved_total = 0
        self.requeues_total = 0
        self.bytes_total = 0
        self.failed_total = 0
        self.last_headroom_delta = 0.0
        # prefill-role engines flag every admitted request for export the
        # moment its prefill lands (engine.py _activate_state)
        for n, eng in self.engines.items():
            if self.roles[n] == "prefill" and getattr(eng, "_migrate_outbox", None) is not None:
                eng.migrate_after_prefill = True

    # -- wiring ------------------------------------------------------------

    def add_remote(self, name: str, target: Any, role: str = "decode") -> None:
        """Register an import-only remote target (an rpc transfer proxy)."""
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; expected one of {ROLES}")
        self._remote[name] = target
        self.roles[name] = role

    def add_engine(self, name: str, eng: Any, role: str = "both") -> None:
        """Elastic join: register a full local engine mid-flight. The next
        tick sees it as both drain target and (if saturated) drain source —
        a freshly warmed engine joining a shedding fleet starts absorbing
        the backlog within one interval, no coordinator restart."""
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; expected one of {ROLES}")
        with self._lock:
            # swap, don't mutate: tick() iterates self.engines lock-free,
            # and in-place insertion mid-iteration would raise
            self.engines = {**self.engines, name: eng}
            self.roles = {**self.roles, name: role}
            if role == "prefill" and getattr(eng, "_migrate_outbox", None) is not None:
                eng.migrate_after_prefill = True
        self._pressure.set()  # drain toward the newcomer now, not next tick

    def note_pressure(self) -> None:
        """Admission-path hook: a shed decision (429) kicks the next tick
        into draining immediately instead of waiting out the interval."""
        self._pressure.set()

    def start(self) -> "MigrationCoordinator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="kv-migration", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pressure.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # unshipped outbox items would otherwise strand their consumers in
        # out.get() forever — error them on the way down
        for eng in self.engines.values():
            outbox = getattr(eng, "_migrate_outbox", None)
            while outbox is not None and not outbox.empty():
                try:
                    item = outbox.get_nowait()
                except Exception:
                    break
                self._fail_item(item, "migration coordinator stopped")

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                log.exception("migration tick failed")
            self._pressure.wait(self.interval_s)
            self._pressure.clear()

    # -- control loop ------------------------------------------------------

    def _headroom(self, eng: Any) -> float | None:
        """Shed-free capacity fraction the drain trigger compares against.

        Two signals, take the min. Pool memory headroom alone is NOT
        enough: paged accounting counts shared prefix blocks once, so a
        uniform workload can hold block usage near zero while every slot
        is busy and the admit queue grows — the exact state a drain
        exists to relieve. Slot headroom measures that queue against a
        1.5x-slots oversubscription cap (the pool's default watermark),
        so a slot-saturated engine reads as drained-out (≈0) only once
        work is actually waiting, and a busy-but-unqueued engine stays
        above drain_low."""
        slot_h = None
        slots = float(getattr(eng, "max_slots", 0) or 0)
        if slots > 0:
            queued = float(eng.queue_depth()) if hasattr(eng, "queue_depth") else 0.0
            slot_h = max(
                0.0, 1.0 - (eng.slots_in_use() + queued) / (1.5 * slots)
            )
        ms = eng.memory_stats()
        if ms.get("enabled"):
            mem_h = float(ms.get("headroom", 0.0))
            return mem_h if slot_h is None else min(mem_h, slot_h)
        return slot_h

    def _targets(self, exclude: str) -> list[tuple[str, float]]:
        """Decode-capable engines by descending headroom, remotes last
        (their headroom is unknown — assume drain_high so a configured
        disaggregation peer is always eligible)."""
        out: list[tuple[str, float]] = []
        for n, eng in self.engines.items():
            if n == exclude or self.roles[n] == "prefill":
                continue
            if getattr(eng, "_migrate_in", None) is None:
                continue  # TPU_MIGRATE off on that engine: cannot import
            h = self._headroom(eng)
            if h is not None:
                out.append((n, h))
        out.sort(key=lambda t: -t[1])
        for n in self._remote:
            if n != exclude and self.roles[n] != "prefill":
                out.append((n, self.drain_high))
        return out

    def _resolve(self, name: str) -> Any:
        return self.engines.get(name) or self._remote[name]

    def _fail_item(self, item: dict[str, Any], msg: str) -> None:
        out = item.get("out")
        if out is None:
            return
        out.put({"type": "error", "error": msg})
        out.put({"type": "done", "finish_reason": "error", "usage": {}})

    def _ship(self, item: dict[str, Any], dest_name: str) -> bool:
        dest = self._resolve(dest_name)
        try:
            dest.migrate_import(item["payload"], out=item.get("out"))
        except Exception as e:
            log.exception("migrate of %s to %s failed", item.get("req_id", "?")[:8], dest_name)
            with self._lock:
                self.failed_total += 1
            self._fail_item(item, f"migration to {dest_name} failed: {e}")
            return False
        with self._lock:
            self.snapshots_moved_total += 1
            self.bytes_total += len(item["payload"])
        return True

    def tick(self) -> None:
        # 1. disaggregated handoff: pump every outbox (prefill-role engines
        # fill them; both-role engines only when a request was explicitly
        # flagged migrate_after_prefill)
        for name, eng in self.engines.items():
            outbox = getattr(eng, "_migrate_outbox", None)
            while outbox is not None and not outbox.empty():
                try:
                    item = outbox.get_nowait()
                except Exception:
                    break
                targets = self._targets(exclude=name)
                if not targets:
                    self._fail_item(item, "no decode-capable migration target")
                    with self._lock:
                        self.failed_total += 1
                    continue
                self._ship(item, targets[0][0])
        # 2. drain: saturated → idle
        rooms = {
            n: h
            for n, eng in self.engines.items()
            if getattr(eng, "_migrate_outbox", None) is not None
            and (h := self._headroom(eng)) is not None
        }
        if rooms:
            lo = min(rooms.values())
            hi = max(rooms.values())
            with self._lock:
                self.last_headroom_delta = hi - lo
            if lo <= self.drain_low:
                src_name = min(rooms, key=rooms.get)  # type: ignore[arg-type]
                targets = [
                    (n, h) for n, h in self._targets(exclude=src_name) if h >= self.drain_high
                ]
                if targets:
                    self._drain(src_name, targets[0][0])

    def _drain(self, src_name: str, dest_name: str) -> None:
        src = self.engines[src_name]
        dest = self._resolve(dest_name)
        for _ in range(self.burst):
            # offloaded snapshots first: they hold committed KV and their
            # consumers have waited longest
            item = src.migrate_export_one()
            if item is not None:
                if self._ship(item, dest_name):
                    log.info(
                        "drained snapshot %s: %s -> %s (%.1f KB)",
                        item.get("req_id", "?")[:8], src_name, dest_name,
                        len(item["payload"]) / 1024,
                    )
                continue
            # then plain queued requests — queued-behind-a-long-tail work
            # needs no KV at all, just a submit on the idle engine (local
            # targets only: the request object carries its consumer queue)
            req = src.migrate_steal_queued()
            if req is None:
                break
            if getattr(req, "migrations", 0) >= 1:
                # already re-homed once: moving it again risks ping-pong
                # (two engines whose headroom recovers alternately bounce
                # the queue head forever) — let it run where it sits
                src.submit(req)
                break
            if not hasattr(dest, "submit"):
                # remote target: cannot re-home a live consumer queue — put
                # the request back where its consumer expects it
                src.submit(req)
                break
            req.migrations = getattr(req, "migrations", 0) + 1
            dest.submit(req)
            with self._lock:
                self.requeues_total += 1
            log.info(
                "requeued %s: %s -> %s (no prefill spent)",
                req.request_id[:8], src_name, dest_name,
            )

    # -- telemetry ---------------------------------------------------------

    def stats(self) -> dict[str, float]:
        with self._lock:
            return {
                "enabled": 1.0,
                "snapshots_moved_total": float(self.snapshots_moved_total),
                "requeues_total": float(self.requeues_total),
                "bytes_total": float(self.bytes_total),
                "failed_total": float(self.failed_total),
                "headroom_delta": float(self.last_headroom_delta),
                "engines": float(len(self.engines)),
                "remotes": float(len(self._remote)),
            }
