"""Byte-level BPE tokenizer over the native (C++) merge core.

In-repo production tokenizer for `tokenizer.json` vocabularies (Llama-3,
GPT-2-lineage byte-level BPE): Python owns the cold path — JSON parsing,
GPT-2 byte↔unicode remapping, regex pretokenization — and `native/
bpe_tokenizer.cpp` owns the hot path (the per-piece merge loop and the
streaming UTF-8 boundary scan). A pure-Python merge loop provides the
fallback when no C++ toolchain exists, and is the equivalence oracle in
tests.

The reference delegates tokenization to llama.cpp inside Ollama
(`worker/llm_worker/main.py:222-243` just reads token counts off the HTTP
response); this module is that native dependency rebuilt in-repo.
"""

from __future__ import annotations

import json
import logging
from functools import lru_cache

log = logging.getLogger("executor.bpe")

# Well-known byte-level BPE pretokenization patterns (public knowledge;
# the `regex` module provides the \p unicode classes).
GPT2_PATTERN = (
    r"'s|'t|'re|'ve|'m|'ll|'d"
    r"| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
)
LLAMA3_PATTERN = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)"
    r"|[^\r\n\p{L}\p{N}]?\p{L}+"
    r"|\p{N}{1,3}"
    r"| ?[^\s\p{L}\p{N}]+[\r\n]*"
    r"|\s*[\r\n]+"
    r"|\s+(?!\S)"
    r"|\s+"
)


def _find_split_pattern(node: dict | None) -> str | None:
    """Walk a pre_tokenizer config for an embedded Split regex (Llama-3
    style tokenizer.json carries its exact pattern there)."""
    if not isinstance(node, dict):
        return None
    if node.get("type") == "Split":
        pat = node.get("pattern") or {}
        return pat.get("Regex") or pat.get("String")
    if node.get("type") == "Sequence":
        for sub in node.get("pretokenizers") or []:
            found = _find_split_pattern(sub)
            if found:
                return found
    return None


@lru_cache(maxsize=1)
def gpt2_byte_to_unicode() -> dict[int, str]:
    """The GPT-2 printable-unicode remapping of raw bytes (standard table)."""
    bs = list(range(ord("!"), ord("~") + 1)) + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


@lru_cache(maxsize=1)
def gpt2_unicode_to_byte() -> dict[str, int]:
    return {c: b for b, c in gpt2_byte_to_unicode().items()}


def token_str_to_bytes(token: str) -> bytes:
    """tokenizer.json vocab strings → raw bytes (undo the GPT-2 remap)."""
    u2b = gpt2_unicode_to_byte()
    out = bytearray()
    for ch in token:
        b = u2b.get(ch)
        if b is None:
            out.extend(ch.encode("utf-8"))  # added/special tokens stay UTF-8
        else:
            out.append(b)
    return bytes(out)


class _PyBpeCore:
    """Pure-Python twin of native/bpe_tokenizer.cpp (fallback + test oracle)."""

    def __init__(self):
        self.token_to_id: dict[bytes, int] = {}
        self.id_to_token: dict[int, bytes] = {}
        self.merges: dict[tuple[int, int], tuple[int, int]] = {}  # pair -> (rank, merged)
        self.byte_ids = [-1] * 256

    def add_token(self, raw: bytes, idx: int) -> None:
        self.token_to_id[raw] = idx
        self.id_to_token[idx] = raw
        if len(raw) == 1:
            self.byte_ids[raw[0]] = idx

    def add_merge(self, left: int, right: int, rank: int, merged: int) -> None:
        self.merges[(left, right)] = (rank, merged)

    def encode_piece(self, piece: bytes) -> list[int]:
        sym = [self.byte_ids[b] for b in piece if self.byte_ids[b] >= 0]
        while len(sym) >= 2:
            best_pos, best_rank, best_id = -1, 1 << 31, -1
            for i in range(len(sym) - 1):
                info = self.merges.get((sym[i], sym[i + 1]))
                if info is not None and info[0] < best_rank:
                    best_rank, best_pos, best_id = info[0], i, info[1]
            if best_pos < 0:
                break
            sym[best_pos : best_pos + 2] = [best_id]
        return sym

    def decode(self, ids: list[int]) -> bytes:
        return b"".join(self.id_to_token.get(i, b"") for i in ids)


class _NativeBpeCore:
    """ctypes wrapper presenting the same surface as _PyBpeCore."""

    def __init__(self, lib):
        import ctypes

        self._ct = ctypes
        self.lib = lib
        self.handle = lib.bpe_new()
        self._id_to_len: dict[int, int] = {}

    def __del__(self):
        try:
            if getattr(self, "handle", None):
                self.lib.bpe_free(self.handle)
        except Exception:
            pass

    def add_token(self, raw: bytes, idx: int) -> None:
        ct = self._ct
        buf = (ct.c_uint8 * max(1, len(raw))).from_buffer_copy(raw or b"\0")
        self.lib.bpe_add_token(self.handle, buf, len(raw), idx)
        self._id_to_len[idx] = len(raw)

    def add_merge(self, left: int, right: int, rank: int, merged: int) -> None:
        self.lib.bpe_add_merge(self.handle, left, right, rank, merged)

    def encode_piece(self, piece: bytes) -> list[int]:
        ct = self._ct
        n = len(piece)
        inp = (ct.c_uint8 * max(1, n)).from_buffer_copy(piece or b"\0")
        out = (ct.c_int32 * max(1, n))()
        wrote = self.lib.bpe_encode(self.handle, inp, n, out, n)
        if wrote < 0:
            return []
        return list(out[:wrote])

    def encode_pieces(self, pieces: list[bytes]) -> list[int]:
        """All pieces in ONE C call — per-call overhead dominates otherwise."""
        ct = self._ct
        data = b"".join(pieces)
        offsets = [0]
        for p in pieces:
            offsets.append(offsets[-1] + len(p))
        n = len(data)
        inp = (ct.c_uint8 * max(1, n)).from_buffer_copy(data or b"\0")
        offs = (ct.c_int32 * len(offsets))(*offsets)
        out = (ct.c_int32 * max(1, n))()
        wrote = self.lib.bpe_encode_batch(self.handle, inp, offs, len(pieces), out, max(1, n))
        if wrote < 0:
            return []
        return list(out[:wrote])

    def decode(self, ids: list[int]) -> bytes:
        ct = self._ct
        n = len(ids)
        if n == 0:
            return b""
        arr = (ct.c_int32 * n)(*ids)
        cap = sum(self._id_to_len.get(i, 0) for i in ids) + 16
        out = (ct.c_uint8 * cap)()
        wrote = self.lib.bpe_decode(self.handle, arr, n, out, cap)
        return bytes(out[:wrote]) if wrote > 0 else b""


def _make_core(force_python: bool = False):
    if not force_python:
        from ..native import load_bpe

        lib = load_bpe()
        if lib is not None:
            return _NativeBpeCore(lib), True
    return _PyBpeCore(), False


class BPETokenizer:
    """tokenizer.json-backed BPE implementing the executor Tokenizer protocol."""

    def __init__(self, path: str, force_python: bool = False):
        # fail fast (before the expensive vocab load) when \p-class regex
        # support is missing — load_tokenizer treats that as "use HF"
        import regex

        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        model = doc.get("model") or {}
        if model.get("type") not in (None, "BPE"):
            raise ValueError(f"unsupported tokenizer model: {model.get('type')}")
        vocab: dict[str, int] = model.get("vocab") or {}
        merges_raw = model.get("merges") or []

        # Byte-level BPE requires full single-byte coverage in the vocab;
        # SentencePiece-converted BPE files ('<0x41>'-style byte tokens)
        # would otherwise silently encode every prompt to nothing.
        byte_coverage = sum(1 for tok in vocab if len(token_str_to_bytes(tok)) == 1)
        if byte_coverage < 256:
            raise ValueError(
                f"not a byte-level BPE vocabulary ({byte_coverage}/256 byte tokens); "
                "use the HF tokenizer backend"
            )

        self.core, self.is_native = _make_core(force_python)
        raw_by_id: dict[int, bytes] = {}
        token_ids: dict[bytes, int] = {}
        for tok, idx in vocab.items():
            raw = token_str_to_bytes(tok)
            self.core.add_token(raw, int(idx))
            raw_by_id[int(idx)] = raw
            token_ids[raw] = int(idx)
        self.special_ids: set[int] = set()
        special_names: dict[str, int] = {}
        for added in doc.get("added_tokens") or []:
            idx = int(added.get("id", -1))
            content = str(added.get("content") or "")
            if idx < 0 or not content:
                continue
            if idx not in raw_by_id:
                raw = content.encode("utf-8")
                self.core.add_token(raw, idx)
                raw_by_id[idx] = raw
                token_ids[raw] = idx
            if added.get("special", True):
                self.special_ids.add(idx)
                special_names[content] = idx

        dropped = 0
        for rank, m in enumerate(merges_raw):
            if isinstance(m, str):
                left_s, _, right_s = m.partition(" ")
            else:
                left_s, right_s = m[0], m[1]
            left_b, right_b = token_str_to_bytes(left_s), token_str_to_bytes(right_s)
            left = token_ids.get(left_b)
            right = token_ids.get(right_b)
            merged = token_ids.get(left_b + right_b)
            if left is None or right is None or merged is None:
                dropped += 1
                continue
            self.core.add_merge(left, right, rank, merged)
        if dropped:
            log.warning("dropped %d merges with out-of-vocab sides", dropped)

        self.vocab_size = max(raw_by_id, default=-1) + 1
        # specials may live in the base vocab rather than added_tokens
        # (GPT-2's <|endoftext|> does); pick from both.
        specials = dict(special_names)
        for raw, i in token_ids.items():
            if raw.startswith(b"<") or raw.startswith(b"["):
                specials.setdefault(raw.decode("utf-8", "replace"), i)
        # -1 = unresolved: never matches a real token, so encode skips the
        # bos prepend and decode never strips a legitimate id-0 vocab token
        # (engine masking already guards with a 0 <= id < vocab check).
        self.bos_id = self._pick(
            specials, "<|begin_of_text|>", "<s>", "[CLS]", "<|im_start|>", "<bos>",
            "<|endoftext|>",
        )
        self.eos_id = self._pick(
            specials, "<|end_of_text|>", "<|eot_id|>", "</s>", "[SEP]", "<|im_end|>",
            "<eos>", "<end_of_turn>", "<|endoftext|>",
        )
        self.pad_id = self._pick(
            specials, "<|finetune_right_pad_id|>", "<pad>", "[PAD]", "<|endoftext|>"
        )
        self.special_ids.update(
            i for i in (self.bos_id, self.eos_id, self.pad_id) if i >= 0
        )

        pre = doc.get("pre_tokenizer")
        pattern = _find_split_pattern(pre) or (
            GPT2_PATTERN if pre and "ByteLevel" in json.dumps(pre) else LLAMA3_PATTERN
        )
        self._pretok = regex.compile(pattern)

    @staticmethod
    def _pick(specials: dict[str, int], *names: str, default: int = -1) -> int:
        for n in names:
            if n in specials:
                return specials[n]
        return default

    # -- protocol ----------------------------------------------------------

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids: list[int] = [self.bos_id] if add_bos and self.bos_id >= 0 else []
        pieces = [p.encode("utf-8") for p in self._pretok.findall(text)]
        if hasattr(self.core, "encode_pieces"):
            ids.extend(self.core.encode_pieces(pieces))
        else:
            for piece in pieces:
                ids.extend(self.core.encode_piece(piece))
        return ids

    def decode(self, ids: list[int]) -> str:
        # all special tokens are stripped from user-visible text, matching
        # HFTokenizer's decode(skip_special_tokens=True) this replaces
        kept = [i for i in ids if i not in self.special_ids]
        return self.core.decode(kept).decode("utf-8", errors="replace")

    def decode_stream(self, pending: bytes, new_ids: list[int]) -> tuple[str, bytes]:
        data = pending + self.core.decode([i for i in new_ids if i not in self.special_ids])
        hold = _utf8_hold(data, self.core)
        if hold:
            return data[:-hold].decode("utf-8", errors="replace"), data[-hold:]
        return data.decode("utf-8", errors="replace"), b""

    def decode_flush(self, pending: bytes) -> str:
        return pending.decode("utf-8", errors="replace") if pending else ""


def _utf8_hold(data: bytes, core) -> int:
    """Trailing incomplete-UTF-8 byte count; native scanner when available."""
    if not data:
        return 0
    if isinstance(core, _NativeBpeCore):
        import ctypes

        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return core.lib.utf8_hold(buf, len(data))
    from .tokenizer import utf8_hold

    return utf8_hold(data)
